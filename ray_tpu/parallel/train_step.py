"""Sharded train-step construction: init, step, and mesh auto-layout.

The jit-compiled training step that every trainer in the Train layer runs.
Parameters/optimizer state are sharded by the model's rules; GSPMD propagates
those shardings through ``optimizer.init`` and the step function, inserting
all-gathers (fsdp), reduce-scatters (grads), and all-reduces (tp) on ICI.
Gradient synchronization never touches the object plane — the property the
reference maintains with NCCL outside Ray (SURVEY.md §3.4), achieved here by
construction.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from ray_tpu.models import llama
from ray_tpu.parallel.mesh import MeshConfig, make_mesh
from ray_tpu.parallel.plan import Plan, compile_plan, compile_step, placement_plan
from ray_tpu.parallel.sharding import ShardingRules
from ray_tpu.util import step_profiler


def default_optimizer(lr: float = 3e-4, weight_decay: float = 0.1,
                      warmup_steps: int = 100, total_steps: int = 10000,
                      grad_clip: float = 1.0) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup_steps, max(total_steps, warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )


def model_family(cfg):
    """The module implementing ``cfg``'s family (init_params / lm_loss /
    sharding_rules) — llama-family dense models or the sparse-MoE family."""
    from ray_tpu.models import moe

    return moe if isinstance(cfg, moe.MoEConfig) else llama


def init_sharded_state(rng: jax.Array, cfg: llama.LlamaConfig, mesh: Mesh,
                       optimizer: optax.GradientTransformation,
                       rules: Optional[ShardingRules] = None):
    """Initialize params+opt state directly into their target shardings.

    Params are produced BY a jitted init with explicit out_shardings, so no
    host-side full copy ever materializes (essential for 7B+); the optimizer
    state inherits the param shardings through GSPMD propagation.
    """
    fam = model_family(cfg)
    rules = rules or fam.sharding_rules(pipeline=cfg.pipeline_axis is not None)
    abstract = jax.eval_shape(lambda r: fam.init_params(r, cfg), rng)
    out_shardings = rules.tree_shardings(abstract, mesh)
    params = jax.jit(lambda r: fam.init_params(r, cfg),
                     out_shardings=out_shardings)(rng)
    opt_state = jax.jit(optimizer.init)(params)
    return params, opt_state


def make_train_step(cfg: llama.LlamaConfig,
                    optimizer: optax.GradientTransformation,
                    loss_fn: Callable = None,
                    mesh: Optional[Mesh] = None,
                    plan: Optional[Plan] = None) -> Callable:
    """(params, opt_state, batch) -> (params, opt_state, metrics), donated.

    ``mesh`` makes itself ambient during tracing (``context.mesh_scope``) so
    model-internal shard_map regions (ring attention, pipeline stages) can
    find it. With a mesh (or an explicit ``plan``), the step compiles
    through the sharding :class:`Plan` — pjit with pinned in/out shardings
    for pure-GSPMD bodies, the shard_map fallback for manual-region bodies
    — instead of re-deriving placement per call site.
    """
    custom_loss = loss_fn is not None
    use_1f1b = not supports_multi_step(cfg)
    if use_1f1b:
        if loss_fn is not None:
            raise ValueError("1f1b computes its own loss inside the "
                             "pipeline; custom loss_fn unsupported")
        if model_family(cfg) is not llama:
            raise NotImplementedError("1f1b schedule: dense llama only")

        def grad_fn(params, batch):
            return llama.lm_loss_and_grads_1f1b(params, batch, cfg)
    else:
        loss_fn = loss_fn or model_family(cfg).lm_loss

        def grad_fn(params, batch):
            return jax.value_and_grad(
                lambda p: loss_fn(p, batch, cfg))(params)

    def step(params, opt_state, batch):
        loss, grads = grad_fn(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        gnorm = optax.global_norm(grads)
        return params, opt_state, {"loss": loss, "grad_norm": gnorm}

    if plan is None and mesh is not None:
        plan = compile_plan(cfg, mesh)
    jstep = compile_step(step, plan,
                         **_plan_shardings(plan, optimizer, custom_loss,
                                           stacked=False))
    return _instrumented(jstep, cfg, mesh, plan=plan)


def _plan_shardings(plan: Optional[Plan], optimizer, custom_loss: bool,
                    stacked: bool) -> Dict[str, Any]:
    """compile_step kwargs for the pjit path: explicit state shardings from
    the plan, the batch pinned by a prefix sharding (batch dim over
    (dp, fsdp) — the same placement ``shard_batch`` applies), metrics
    replicated. A custom loss_fn may train custom params the family rules
    don't describe, so it stays on sharding inference."""
    from ray_tpu.parallel.plan import PJIT

    if plan is None or plan.mode != PJIT or custom_loss:
        return {}
    params_sh, opt_sh = plan.state_shardings(optimizer)
    batch_sh = plan.batch_sharding(2, False, stacked)
    return {"in_shardings": (params_sh, opt_sh, batch_sh),
            "out_shardings": (params_sh, opt_sh, plan.replicated())}


def supports_multi_step(cfg) -> bool:
    """Whether ``make_multi_step`` can fuse K steps for this config — the
    1f1b schedule's manual interleave cannot ride a ``lax.scan`` carry, so
    fused drivers must degrade to single-step there."""
    return not (getattr(cfg, "pipeline_axis", None) is not None
                and getattr(cfg, "pipeline_schedule", "gpipe") == "1f1b")


def _batch_tokens(batch, stacked: bool = False) -> Tuple[int, int]:
    """(trained tokens, seq len) of one step's batch. Token batches are
    [B, S+1] ([K, B, S+1] stacked): S positions train per row. Custom
    loss_fn batches without a usable token-shaped leaf yield (0, 1) — the
    profiler then records times without tokens/MFU instead of crashing
    the training loop it instruments."""
    need = 3 if stacked else 2
    leaf = batch.get("tokens") if isinstance(batch, dict) else None
    if leaf is None or getattr(leaf, "ndim", 0) < need:
        cands = [x for x in jax.tree.leaves(batch)
                 if getattr(x, "ndim", 0) >= need]
        if not cands:
            return 0, 1
        leaf = cands[0]
    if stacked:
        k, b, s1 = leaf.shape[0], leaf.shape[1], leaf.shape[2]
        return k * b * max(1, s1 - 1), max(1, s1 - 1)
    b, s1 = leaf.shape[0], leaf.shape[1]
    return b * max(1, s1 - 1), max(1, s1 - 1)


_PROGRAM_IDS = __import__("itertools").count()


def _instrumented(jstep, cfg, mesh, stacked: bool = False,
                  steps_per_launch: int = 1, plan: Optional[Plan] = None):
    """The (params, opt_state, batch) entry point every trainer calls:
    ambient-mesh plumbing plus the step profiler's per-step record (wall /
    compile / dispatch / device-sync split, analytic MFU). Disabled
    profiling costs one predicate per step. The profiler key is a fresh
    counter value per built step — NOT id(jstep), which CPython reuses
    after GC and would book a new program's compile as dispatch."""
    program_id = next(_PROGRAM_IDS)

    def call(params, opt_state, batch):
        if mesh is None:
            return jstep(params, opt_state, batch)
        from ray_tpu.parallel.context import mesh_scope

        with mesh_scope(mesh):
            return jstep(params, opt_state, batch)

    def run(params, opt_state, batch):
        if not step_profiler.is_enabled():
            return call(params, opt_state, batch)
        from ray_tpu.util import flops as F

        tokens, seq = _batch_tokens(batch, stacked)
        return step_profiler.profiled_call(
            "train", call, (params, opt_state, batch),
            key=("train", program_id), tokens=tokens,
            steps=steps_per_launch,
            flops=tokens * F.train_flops_per_token(cfg, seq))

    # the compiled program and plan ride along so drivers can assert
    # single-launch fusion via the jit cache and reuse the placement plan
    run._jit = jstep
    run._plan = plan
    return run


def make_multi_step(cfg: llama.LlamaConfig,
                    optimizer: optax.GradientTransformation,
                    n_steps: int,
                    loss_fn: Callable = None,
                    mesh: Optional[Mesh] = None,
                    plan: Optional[Plan] = None) -> Callable:
    """K train steps fused into ONE compiled program via ``lax.scan``.

    (params, opt_state, batches) -> (params, opt_state, metrics) where each
    leaf of ``batches`` is stacked [K, ...] (one slice per step) and
    ``metrics`` holds per-step [K] arrays.

    TPU-idiomatic launch amortization: one dispatch executes K optimizer
    steps back to back on-device, so per-launch host/runtime overhead
    (dispatch, tunnel round trips, XLA launch latency) is paid once per K
    steps instead of per step — the standard trick for host-bound training
    loops (and the instrument that separates per-launch overhead from true
    device time in bench.py's sweep: scan-per-step vs single-step marginal).
    Works under any mesh: the scanned body is the same sharded step GSPMD
    already compiles.
    """
    if not supports_multi_step(cfg):
        raise NotImplementedError("multi-step scan over the 1f1b schedule "
                                  "is unsupported; use gpipe or single-step "
                                  "(StepDriver degrades automatically)")
    custom_loss = loss_fn is not None
    loss_fn = loss_fn or model_family(cfg).lm_loss

    def body(carry, batch):
        params, opt_state = carry
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch, cfg))(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), {"loss": loss,
                                     "grad_norm": optax.global_norm(grads)}

    def steps(params, opt_state, batches):
        (params, opt_state), metrics = jax.lax.scan(
            body, (params, opt_state), batches, length=n_steps)
        return params, opt_state, metrics

    if plan is None and mesh is not None:
        plan = compile_plan(cfg, mesh)
    jsteps = compile_step(steps, plan,
                          **_plan_shardings(plan, optimizer, custom_loss,
                                            stacked=True))
    return _instrumented(jsteps, cfg, mesh, stacked=True,
                         steps_per_launch=n_steps, plan=plan)


def shard_batch(batch: Dict[str, jax.Array], mesh: Mesh,
                stacked: bool = False) -> Dict[str, jax.Array]:
    """Place a host batch onto the mesh: batch dim over (dp, fsdp), sequence
    over sp when the mesh has a non-trivial sp axis (context parallelism).
    ``stacked=True`` handles multi-step batches [K, B, ...] (make_multi_step):
    the leading step axis stays replicated, batch/seq shard as usual.

    Delegates to the per-mesh cached :class:`Plan` (``plan.placement_plan``)
    so the NamedShardings are derived once per mesh, not per call.
    Sequence rides sp only when it divides evenly (token batches are
    [B, S+1] — odd — so they stay seq-replicated; GSPMD re-shards the
    [B, S] slice at the shard_map boundary)."""
    return placement_plan(mesh).place_batch(batch, stacked=stacked)


def auto_mesh(n_devices: int, devices=None, *, tp: Optional[int] = None,
              sp: int = 1, pp: int = 1, dp: int = 1, ep: int = 1
              ) -> Tuple[Mesh, MeshConfig]:
    """A sensible layout for n devices: fsdp-dominant with a tp=min(4, n)
    inner axis when n allows — the FSDP+TP sweet spot at the 7B scale.
    sp/pp/ep carve off sequence/pipeline/expert axes."""
    if tp is None:
        tp = 1
        for cand in (4, 2):
            if (n_devices % (cand * sp * pp * dp * ep) == 0
                    and n_devices >= cand * 2):
                tp = cand
                break
    cfg = MeshConfig.for_devices(n_devices, tp=tp, sp=sp, pp=pp, dp=dp, ep=ep)
    return make_mesh(cfg, devices), cfg
