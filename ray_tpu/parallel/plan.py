"""Sharding-plan compiler: shardings as a first-class, carried object.

Before this module, every call site re-derived placement on its own:
``shard_batch`` rebuilt ``NamedSharding`` objects per batch, the step
builders left in/out shardings to GSPMD inference, and the donated-carry
convention (``donate_argnums=(0, 1)``) was repeated at each ``jax.jit``
call. A :class:`Plan` computes all of that ONCE per (config, mesh) and
carries it through ``compile_step`` → ``make_train_step`` /
``make_multi_step`` → batch placement — the Ray-Train analogy is the
placement group the Train layer carries instead of re-solving placement
per task (arxiv 1712.05889), applied to shardings.

Mode selection (the SNIPPETS ``compile_step_with_plan`` shape): a step
function whose traced body is pure GSPMD compiles under **pjit** with the
plan's explicit in/out shardings pinned; a body containing manual
``shard_map`` regions (pipeline stages, ring/Ulysses attention over the
``sp`` axis) compiles under the **shard_map** fallback — a plain ``jit``
whose manual regions bind the ambient mesh (``context.mesh_scope``), since
pinning top-level shardings across manual regions over-constrains GSPMD.

The ``jax-purity`` lint checker guards every body compiled here: a host
sync (``.item()`` / ``np.asarray`` / ``float()``) inside the traced step
is a machine-checked finding, not a code-review hope.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Dict, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PJIT = "pjit"
SHARD_MAP = "shard_map"


class PlanError(ValueError):
    """A plan/compile request that cannot be satisfied, with a hint."""

    def __init__(self, message: str, hint: str = ""):
        super().__init__(message + (f" ({hint})" if hint else ""))
        self.hint = hint


def plan_mode(cfg: Any, mesh: Optional[Mesh]) -> str:
    """Pick pjit vs shard_map for ``cfg``'s step function.

    shard_map when the traced body contains manual-collective regions that
    bind the ambient mesh: a pipeline axis (gpipe/1f1b stages), a
    non-trivial ``sp`` mesh axis, or a sequence-parallel attention impl.
    Everything else is pure GSPMD → pjit with explicit shardings.
    """
    if getattr(cfg, "pipeline_axis", None) is not None:
        return SHARD_MAP
    if getattr(cfg, "attn_impl", "") in ("ring", "ulysses"):
        return SHARD_MAP
    if mesh is not None and mesh.shape.get("sp", 1) > 1:
        return SHARD_MAP
    return PJIT


@dataclasses.dataclass
class Plan:
    """In/out shardings + donation policy for one (config, mesh) pair.

    State shardings (params + optimizer state) are derived lazily from the
    model family's :class:`ShardingRules` via ``eval_shape`` — no params
    ever materialize — and cached. Batch placements are cached per
    (rank, seq-divisibility, stacked) key so repeated ``place_batch``
    calls reuse the same ``NamedSharding`` objects instead of
    reconstructing them per step.
    """

    mesh: Mesh
    mode: str                       # PJIT | SHARD_MAP
    cfg: Any = None                 # model config (state-sharding source)
    rules: Any = None               # ShardingRules (lazy from cfg's family)
    donate_argnums: Tuple[int, ...] = (0, 1)   # donated carries

    def __post_init__(self):
        self._lock = threading.Lock()
        # rt: guarded-by(_lock)
        self._batch_cache: Dict[Tuple, NamedSharding] = {}
        # rt: guarded-by(_lock)
        self._state_shardings: Dict[int, Tuple[Any, Any]] = {}
        # rt: guarded-by(_lock)
        self._opt_refs: Dict[int, Any] = {}

    # ---- state (params / opt_state) shardings -------------------------------
    def _rules(self):
        if self.rules is None:
            from ray_tpu.parallel.train_step import model_family

            fam = model_family(self.cfg)
            self.rules = fam.sharding_rules(
                pipeline=getattr(self.cfg, "pipeline_axis", None) is not None)
        return self.rules

    def state_shardings(self, optimizer) -> Tuple[Any, Any]:
        """(params_shardings, opt_state_shardings) trees for ``cfg`` under
        ``optimizer`` — computed once per optimizer identity via
        ``eval_shape`` (abstract; no arrays allocated)."""
        key = id(optimizer)
        with self._lock:
            # pin the optimizer so a collected object can't hand its id
            # (and this cache entry) to a different optimizer
            self._opt_refs[key] = optimizer
            hit = self._state_shardings.get(key)
        if hit is not None:
            return hit
        from ray_tpu.parallel.train_step import model_family

        fam = model_family(self.cfg)
        rules = self._rules()
        abstract = jax.eval_shape(lambda r: fam.init_params(r, self.cfg),
                                  jax.random.key(0))
        params_sh = rules.tree_shardings(abstract, self.mesh)
        # optimizer-state paths embed the param subtree paths (mu/nu/...),
        # so the same path rules resolve them; scalars fall to replicated
        opt_abstract = jax.eval_shape(optimizer.init, abstract)
        opt_sh = rules.tree_shardings(opt_abstract, self.mesh)
        out = (params_sh, opt_sh)
        with self._lock:
            self._state_shardings[key] = out
        return out

    # ---- batch placement ----------------------------------------------------
    def batch_sharding(self, ndim: int, shard_seq: bool,
                       stacked: bool) -> NamedSharding:
        """The cached NamedSharding for one batch leaf: batch dim over
        (dp, fsdp); sequence over sp when it divides (shard_seq); a
        stacked [K, ...] leaf keeps its leading step axis replicated."""
        key = (ndim, shard_seq, stacked)
        with self._lock:
            sh = self._batch_cache.get(key)
            if sh is None:
                lead = (None,) if stacked else ()
                if shard_seq:
                    spec = P(*lead, ("dp", "fsdp"), "sp")
                else:
                    spec = P(*lead, ("dp", "fsdp"))
                sh = NamedSharding(self.mesh, spec)
                self._batch_cache[key] = sh
            return sh

    def place_batch(self, batch: Any, stacked: bool = False) -> Any:
        """Place a host batch onto the mesh (the one implementation behind
        ``train_step.shard_batch``): batch dim over (dp, fsdp), sequence
        over a non-trivial sp axis when it divides evenly. ``stacked``
        handles multi-step batches [K, B, ...]."""
        sp = self.mesh.shape.get("sp", 1)
        bdim = 1 if stacked else 0

        def place(x):
            shard_seq = (x.ndim >= bdim + 2 and sp > 1
                         and x.shape[bdim + 1] % sp == 0)
            target = self.batch_sharding(x.ndim, shard_seq, stacked)
            if getattr(x, "sharding", None) == target:
                return x  # already placed (pre-stacked device feed)
            return jax.device_put(x, target)

        return jax.tree.map(place, batch)

    def replicated(self) -> NamedSharding:
        """The fully-replicated sharding (metrics outputs)."""
        with self._lock:
            sh = self._batch_cache.get("replicated")
            if sh is None:
                sh = NamedSharding(self.mesh, P())
                self._batch_cache["replicated"] = sh
            return sh


def compile_plan(cfg: Any, mesh: Mesh, rules: Any = None,
                 donate_argnums: Tuple[int, ...] = (0, 1)) -> Plan:
    """Build the sharding plan for ``cfg`` on ``mesh``."""
    if mesh is None:
        raise PlanError("compile_plan needs a mesh",
                        "pass the Mesh the step will run under")
    return Plan(mesh=mesh, mode=plan_mode(cfg, mesh), cfg=cfg, rules=rules,
                donate_argnums=donate_argnums)


def compile_step(body: Callable, plan: Optional[Plan], *,
                 in_shardings: Any = None, out_shardings: Any = None,
                 donate_argnums: Optional[Tuple[int, ...]] = None,
                 static_argnums: Tuple[int, ...] = ()) -> Callable:
    """Compile one step function under the plan.

    pjit mode: ``jax.jit`` with the plan's explicit in/out shardings
    (both or neither — one without the other is a config bug, the
    SNIPPETS contract). shard_map mode: plain ``jax.jit`` with donation
    only; the body's manual regions bind the ambient ``mesh_scope`` and
    GSPMD infers the rest from the (already plan-placed) arguments.

    No plan ⇒ legacy single-process behavior (``jax.jit`` + donation),
    so mesh-less callers (unit profiling, host-only tests) keep working.
    """
    donate = donate_argnums if donate_argnums is not None else \
        (plan.donate_argnums if plan is not None else (0, 1))
    kwargs: Dict[str, Any] = {"donate_argnums": donate}
    if static_argnums:
        kwargs["static_argnums"] = static_argnums
    if plan is None or plan.mode == SHARD_MAP:
        if (in_shardings is None) != (out_shardings is None):
            raise PlanError(
                "compile_step requires both in_shardings and out_shardings "
                "when either is given",
                "pass both or neither; shard_map mode infers from args")
        return jax.jit(body, **kwargs)
    if (in_shardings is None) != (out_shardings is None):
        raise PlanError(
            "compile_step requires both in_shardings and out_shardings "
            "when using pjit",
            "pass both sharding arguments or omit them to infer from args")
    if in_shardings is not None:
        kwargs["in_shardings"] = in_shardings
        kwargs["out_shardings"] = out_shardings
    return jax.jit(body, **kwargs)


# ---- per-mesh placement-plan cache ------------------------------------------
# shard_batch (train_step.py) is called per batch from every trainer loop;
# the plan cache keeps one placement Plan per mesh so the NamedShardings
# are derived once, not per call site.
_placement_lock = threading.Lock()
_placement_plans: Dict[Mesh, Plan] = {}  # rt: guarded-by(_placement_lock)


def placement_plan(mesh: Mesh) -> Plan:
    """The cached batch-placement plan for ``mesh`` (mode-agnostic)."""
    with _placement_lock:
        plan = _placement_plans.get(mesh)
        if plan is None:
            if len(_placement_plans) > 64:  # meshes are few; tests make many
                _placement_plans.clear()
            plan = Plan(mesh=mesh, mode=PJIT)
            _placement_plans[mesh] = plan
        return plan
