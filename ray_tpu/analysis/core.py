"""Framework core: findings, the checker registry, and the shared
per-file AST cache every checker reads from.

One parse per file per run (mtime-keyed, so repeated ``rt lint`` calls in
a session reparse only what changed); ``# rt:`` directive comments are
extracted with ``tokenize`` in the same pass so checkers never rescan
source text themselves.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple, Type

#: repo root: ``<root>/ray_tpu/analysis/core.py`` -> ``<root>``
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SEVERITIES = ("error", "warning")

# Comment grammar (anchored at the start of the comment so prose that
# merely *mentions* a directive can't arm one). Directives:
#   ``rt: lint-allow(checker[, ...])`` — suppress findings on this line
#   ``rt: guarded-by(_lock)``          — attr on this line is guarded
#   ``rt: hot-module``                 — whole module is dispatch-hot
_DIRECTIVE = re.compile(r"\A#+\s*rt:\s*([a-z-]+)\s*(?:\(([^)]*)\))?")


@dataclass
class Finding:
    """One rule violation, printable as ``path:line: [checker] message``.

    ``scope``/``detail`` (not line numbers) feed the baseline fingerprint,
    so unrelated edits that shift lines don't invalidate the committed
    suppressions — the ratchet tracks *what* is suppressed, not where it
    happened to sit.
    """

    checker: str
    path: str            # repo-relative, '/'-separated
    line: int
    message: str
    severity: str = "error"
    hint: str = ""       # how to fix it (one line)
    scope: str = ""      # enclosing def/class qualname
    detail: str = ""     # stable discriminator (lock name, import, ...)

    def fingerprint(self) -> str:
        return "::".join((self.checker, self.path, self.scope,
                          self.detail or self.message))

    def render(self) -> str:
        sev = "" if self.severity == "error" else " (warning)"
        out = f"{self.path}:{self.line}: [{self.checker}]{sev} {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out

    def to_dict(self) -> Dict:
        return {"checker": self.checker, "path": self.path,
                "line": self.line, "severity": self.severity,
                "message": self.message, "hint": self.hint,
                "scope": self.scope, "detail": self.detail,
                "fingerprint": self.fingerprint()}


@dataclass
class ModuleInfo:
    """Parsed view of one source file, shared across checkers."""

    path: str                      # absolute
    relpath: str                   # repo-relative, '/'-separated
    source: str
    tree: ast.Module
    #: line -> checker names allowed there ('*' = all)
    allow: Dict[int, Set[str]] = field(default_factory=dict)
    #: line -> guarded-by lock name declared on that line
    guarded: Dict[int, str] = field(default_factory=dict)
    hot: bool = False              # '# rt: hot-module' seen
    #: function/async-function node -> dotted qualname; built lazily
    _qualnames: Optional[Dict[ast.AST, str]] = None
    _parents: Optional[Dict[ast.AST, ast.AST]] = None
    _lines: Optional[List[str]] = None

    # -- scope helpers --------------------------------------------------------
    def qualnames(self) -> Dict[ast.AST, str]:
        """def/class node -> dotted qualname (``Cls.method.inner``)."""
        if self._qualnames is None:
            out: Dict[ast.AST, str] = {}

            def walk(node: ast.AST, prefix: str) -> None:
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef)):
                        q = f"{prefix}.{child.name}" if prefix \
                            else child.name
                        out[child] = q
                        walk(child, q)
                    else:
                        walk(child, prefix)

            walk(self.tree, "")
            self._qualnames = out
        return self._qualnames

    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {c: p for p in ast.walk(self.tree)
                             for c in ast.iter_child_nodes(p)}
        return self._parents

    def scope_of(self, node: ast.AST) -> str:
        """Qualname of the innermost def/class enclosing ``node``."""
        qn, parents = self.qualnames(), self.parents()
        cur: Optional[ast.AST] = node
        while cur is not None:
            if cur in qn:
                return qn[cur]
            cur = parents.get(cur)
        return "<module>"

    def functions(self) -> List[Tuple[str, ast.AST]]:
        """Every (qualname, def-node), methods and nested defs included."""
        return [(q, n) for n, q in self.qualnames().items()
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

    def allowed(self, line: int, checker: str) -> bool:
        """True when the line — or the contiguous comment block directly
        above it (the natural home when the construct spans lines) —
        carries a ``lint-allow`` for this checker."""
        def hit(ln: int) -> bool:
            names = self.allow.get(ln)
            return bool(names) and (checker in names or "*" in names)

        if hit(line):
            return True
        if self._lines is None:
            self._lines = self.source.splitlines()
        lines = self._lines
        ln = line - 1
        while 1 <= ln <= len(lines) and lines[ln - 1].lstrip().startswith(
                "#"):
            if hit(ln):
                return True
            ln -= 1
        return False


def _parse_directives(source: str, mod: ModuleInfo) -> None:
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _DIRECTIVE.match(tok.string)
            if m:
                name, args = m.group(1), (m.group(2) or "")
                if name == "lint-allow":
                    names = {a.strip() for a in args.split(",") if a.strip()}
                    mod.allow.setdefault(tok.start[0], set()).update(
                        names or {"*"})
                elif name == "guarded-by" and args.strip():
                    mod.guarded[tok.start[0]] = args.strip()
                elif name == "hot-module":
                    mod.hot = True
    except tokenize.TokenizeError:
        pass  # the ast parse above already succeeded; directives best-effort


# -- per-file cache -----------------------------------------------------------
_CACHE: Dict[str, Tuple[Tuple[float, int], ModuleInfo]] = {}


def load_module(path: str) -> ModuleInfo:
    """Parse ``path`` (or return the cached parse if unchanged)."""
    path = os.path.abspath(path)
    st = os.stat(path)
    key = (st.st_mtime, st.st_size)
    hit = _CACHE.get(path)
    if hit is not None and hit[0] == key:
        return hit[1]
    with open(path, encoding="utf-8") as f:
        source = f.read()
    rel = os.path.relpath(path, REPO_ROOT).replace(os.sep, "/")
    tree = ast.parse(source, filename=path)  # SyntaxError -> caller
    mod = ModuleInfo(path=path, relpath=rel, source=source, tree=tree)
    _parse_directives(source, mod)
    _CACHE[path] = (key, mod)
    return mod


def clear_cache() -> None:
    _CACHE.clear()


# -- checker registry ---------------------------------------------------------
class Checker:
    """One invariant. Subclass, set ``name``/``description``, implement
    ``check_module`` (per file) and/or ``finalize`` (cross-file, runs once
    after every module was visited)."""

    name: str = ""
    description: str = ""
    default_severity: str = "error"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        return ()

    def finalize(self, mods: List[ModuleInfo],
                 root: str) -> Iterable[Finding]:
        return ()


_REGISTRY: Dict[str, Type[Checker]] = {}


def register(cls: Type[Checker]) -> Type[Checker]:
    if not cls.name:
        raise ValueError(f"checker {cls.__name__} needs a name")
    if cls.name in _REGISTRY and _REGISTRY[cls.name] is not cls:
        raise ValueError(f"duplicate checker name {cls.name!r}")
    _REGISTRY[cls.name] = cls
    return cls


def all_checkers() -> Dict[str, Checker]:
    """name -> instance, with the bundled checkers registered."""
    from ray_tpu.analysis import checkers as _bundled  # noqa: F401

    return {name: cls() for name, cls in sorted(_REGISTRY.items())}


# -- shared rule tables -------------------------------------------------------
#: thread-blocking calls, shared by lock-discipline (blocking under a held
#: lock) and event-loop-blocking (blocking on the loop) so the two checkers
#: can never diverge on what "blocking" means. name -> async-side fix hint.
BLOCKING_CALLS: Dict[str, str] = {
    "time.sleep": "asyncio.sleep",
    "ray_tpu.get": "await the ref's future, or run_in_executor",
    "ray_tpu.wait": "await, or run_in_executor",
    "rt.get": "await the ref's future, or run_in_executor",
    "rt.wait": "await, or run_in_executor",
    "subprocess.run": "asyncio.create_subprocess_exec",
    "subprocess.call": "asyncio.create_subprocess_exec",
    "subprocess.check_output": "asyncio.create_subprocess_exec",
    "subprocess.check_call": "asyncio.create_subprocess_exec",
    "urllib.request.urlopen": "aiohttp (or run_in_executor)",
    "urlopen": "aiohttp (or run_in_executor)",
    "requests.get": "aiohttp",
    "requests.post": "aiohttp",
    "requests.put": "aiohttp",
    "requests.delete": "aiohttp",
    "requests.request": "aiohttp",
    "socket.create_connection": "loop.sock_connect / open_connection",
}


# -- shared AST utilities -----------------------------------------------------
def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def in_type_checking_block(mod: ModuleInfo, node: ast.AST) -> bool:
    parents = mod.parents()
    cur: Optional[ast.AST] = node
    while cur is not None:
        if isinstance(cur, ast.If):
            t = cur.test
            name = dotted_name(t) if isinstance(
                t, (ast.Name, ast.Attribute)) else None
            if name in ("TYPE_CHECKING", "typing.TYPE_CHECKING"):
                return True
        cur = parents.get(cur)
    return False
