"""``rt lint`` — the concurrency- and runtime-invariant static-analysis plane.

Reference analog: the C++ core enforces its threading invariants with
compile-time tooling (``ABSL_GUARDED_BY``, thread-check annotations,
event-loop discipline lints); this package is the Python twin for a
runtime whose worst bug classes have all been *invariant* violations a
targeted AST pass would have caught before review:

  - a lock acquired from weakref-finalizer/GC context (the object-ledger
    self-deadlock that wedged a serve proxy for 10+ minutes),
  - locks held across RPC / ``ray_tpu.get`` (the serve controller booting
    proxies under the lock every status poll contends on),
  - blocking calls on the event loop, swallowed ``CancelledError`` in
    stream pumps, function-local imports on dispatch hot paths, and host
    syncs inside ``jax.jit``-traced step functions.

Layout:

  - :mod:`ray_tpu.analysis.core` — ``Finding``, ``Checker`` registry,
    ``ModuleInfo`` (AST + ``# rt:`` directive comments) with a per-file
    mtime-keyed cache shared by every checker;
  - :mod:`ray_tpu.analysis.baseline` — the committed suppression file
    (``scripts/lint_baseline.json``): existing debt is *ratcheted* — new
    findings fail, baselined ones are tracked and burned down;
  - :mod:`ray_tpu.analysis.runner` — discovery + orchestration +
    the ``rt lint [--json] [--baseline-update] [paths...]`` CLI;
  - :mod:`ray_tpu.analysis.checkers` — the project-specific checkers.

Inline escape hatch (for *deliberate, reviewed* idioms only — legacy debt
belongs in the baseline where it stays visible):

  some_call()  # rt: lint-allow(checker-name) why this is safe
"""

from ray_tpu.analysis.core import (  # noqa: F401
    Checker,
    Finding,
    ModuleInfo,
    all_checkers,
    load_module,
    register,
)
from ray_tpu.analysis.runner import run_lint  # noqa: F401
