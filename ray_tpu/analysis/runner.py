"""Discovery + orchestration + the ``rt lint`` CLI.

``rt lint`` with no paths scans ``ray_tpu/`` against the committed
baseline and exits 0 only when no *new* finding exists — the tier-1 gate
(``tests/test_zz_lint.py``) and the ``chaos_smoke.sh`` pre-flight both
run exactly this. ``rt lint path/to/file.py`` scopes the scan (baseline
still applies); ``--baseline-update`` rewrites the baseline to current
reality after debt is paid down (or, rarely, consciously taken on).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Sequence

from ray_tpu.analysis import baseline as B
from ray_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    REPO_ROOT,
    all_checkers,
    load_module,
)

_SKIP_DIRS = {"__pycache__", ".git", ".seedcheck", "node_modules"}
DEFAULT_SCAN = os.path.join(REPO_ROOT, "ray_tpu")


def discover(paths: Sequence[str]) -> List[str]:
    # dict-as-ordered-set: overlapping arguments (`rt lint pkg pkg/f.py`)
    # must not scan a file twice — duplicate findings would exceed the
    # baseline's fingerprint counts and fail a clean tree
    out: Dict[str, None] = {}
    for p in paths:
        p = os.path.abspath(p)
        if not os.path.exists(p):
            # a typo'd path scanning zero files would exit 0 as a false
            # clean pass — refuse instead
            raise SystemExit(f"rt lint: no such file or directory: {p}")
        if os.path.isfile(p):
            out[p] = None
            continue
        for dirpath, dirnames, files in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            for fn in sorted(files):
                if fn.endswith(".py"):
                    out[os.path.join(dirpath, fn)] = None
    return list(out)


def run_lint(paths: Optional[Sequence[str]] = None,
             select: Optional[Sequence[str]] = None,
             baseline_path: str = B.DEFAULT_BASELINE,
             use_baseline: bool = True) -> Dict:
    """-> {'findings': [new Finding...], 'suppressed': [...], 'stale': {},
    'all': [...], 'files': n, 'checkers': [names]}"""
    full_run = paths is None or not list(paths)
    files = discover([DEFAULT_SCAN] if full_run else list(paths))
    checkers = all_checkers()
    if select:
        unknown = set(select) - set(checkers)
        if unknown:
            raise SystemExit(f"rt lint: unknown checker(s) "
                             f"{sorted(unknown)}; known: "
                             f"{sorted(checkers)}")
        checkers = {k: v for k, v in checkers.items() if k in select}

    mods: List[ModuleInfo] = []
    findings: List[Finding] = []
    for path in files:
        try:
            mod = load_module(path)
        except SyntaxError as e:
            rel = os.path.relpath(path, REPO_ROOT).replace(os.sep, "/")
            findings.append(Finding(
                checker="parse", path=rel, line=e.lineno or 1,
                message=f"does not parse: {e.msg}", scope="<module>",
                detail="syntax-error"))
            continue
        mods.append(mod)
        for checker in checkers.values():
            findings.extend(checker.check_module(mod))
    # repo-level finalizers only make sense over the whole tree (or when
    # the checker was asked for by name on a scoped run)
    for name, checker in checkers.items():
        if full_run or (select and name in select):
            findings.extend(checker.finalize(mods, REPO_ROOT))

    # central inline-allow enforcement (checkers also do this themselves,
    # but a finding built without consulting the line must still respect
    # the source's say-so)
    by_path = {m.relpath: m for m in mods}
    findings = [f for f in findings
                if not (f.path in by_path
                        and by_path[f.path].allowed(f.line, f.checker))]
    findings.sort(key=lambda f: (f.path, f.line, f.checker))

    base = B.load(baseline_path) if use_baseline else {}
    new, suppressed, stale = B.split(findings, base)
    if not full_run:
        # a scoped scan only sees part of the tree: baseline entries for
        # files outside the scope are not "debt paid down", they are
        # simply out of view — stale is a full-tree verdict
        stale = {}
    return {"findings": new, "suppressed": suppressed, "stale": stale,
            "all": findings, "files": len(files),
            "checkers": sorted(all_checkers() if not select else select)}


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="rt lint",
        description="concurrency- and runtime-invariant static analysis "
                    "with a ratcheted baseline")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to scan (default: ray_tpu/)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings")
    parser.add_argument("--baseline", default=B.DEFAULT_BASELINE,
                        help="suppression file "
                             "(default scripts/lint_baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, suppressed or not")
    parser.add_argument("--baseline-update", action="store_true",
                        help="rewrite the baseline to current findings "
                             "(full-tree scan) and exit 0")
    parser.add_argument("--select", action="append", metavar="CHECKER",
                        help="run only these checkers (repeatable)")
    parser.add_argument("--list-checkers", action="store_true")
    args = parser.parse_args(argv)

    if args.list_checkers:
        for name, checker in all_checkers().items():
            print(f"{name:<22} {checker.description}")
        return 0

    if args.baseline_update and (args.paths or args.select):
        # a partial scan sees a partial finding set: writing it out would
        # silently wipe every out-of-scope suppression from the ratchet
        print("rt lint: --baseline-update requires a full-tree, "
              "all-checkers scan (drop the path arguments / --select)",
              file=sys.stderr)
        return 2

    result = run_lint(paths=args.paths, select=args.select,
                      baseline_path=args.baseline,
                      use_baseline=not args.no_baseline
                      and not args.baseline_update)

    if args.baseline_update:
        counts = B.save(args.baseline, result["all"])
        print(f"baseline updated: {len(result['all'])} finding(s) across "
              f"{len(counts)} fingerprint(s) -> {args.baseline}")
        return 0

    new: List[Finding] = result["findings"]
    if args.json:
        print(json.dumps({
            "findings": [f.to_dict() for f in new],
            "suppressed": len(result["suppressed"]),
            "stale_baseline_entries": result["stale"],
            "files_scanned": result["files"],
            "checkers": result["checkers"],
            "exit_code": 1 if new else 0,
        }, indent=2))
        return 1 if new else 0

    for f in new:
        print(f.render())
    tail = (f"{result['files']} file(s), "
            f"{len(result['checkers'])} checker(s): "
            f"{len(new)} new finding(s), "
            f"{len(result['suppressed'])} baselined")
    if result["stale"]:
        tail += (f", {sum(result['stale'].values())} stale baseline "
                 f"entr(ies) — debt paid down; run --baseline-update to "
                 f"shrink the file")
    print(("FAIL: " if new else "OK: ") + tail,
          file=sys.stderr if new else sys.stdout)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
