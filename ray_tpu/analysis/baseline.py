"""Baseline suppression file: the ratchet.

The committed file (``scripts/lint_baseline.json``) maps finding
fingerprints to the count of pre-existing occurrences. A run fails only
on occurrences *beyond* the baselined count — new debt can't land, old
debt stays visible (``rt lint`` prints the suppressed tally) and burns
down: ``--baseline-update`` rewrites the file to current reality, which
CI diffs will only ever show shrinking unless a PR explicitly argues for
new suppressions.

Fingerprints are line-independent (checker/path/scope/detail), so
mechanical edits that shift code don't churn the file.
"""

from __future__ import annotations

import collections
import json
import os
from typing import Dict, List, Tuple

from ray_tpu.analysis.core import Finding, REPO_ROOT

DEFAULT_BASELINE = os.path.join(REPO_ROOT, "scripts", "lint_baseline.json")


def load(path: str) -> Dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    sup = doc.get("suppressions", {})
    if not isinstance(sup, dict):
        raise ValueError(f"{path}: 'suppressions' must be an object")
    return {str(k): int(v) for k, v in sup.items()}


def save(path: str, findings: List[Finding]) -> Dict[str, int]:
    counts: Dict[str, int] = collections.Counter(
        f.fingerprint() for f in findings)
    doc = {
        "comment": "rt lint ratchet: pre-existing findings, tracked for "
                   "burn-down. New findings FAIL; shrink this file with "
                   "`rt lint --baseline-update` after paying debt down. "
                   "Growing it is a reviewed decision, not a reflex.",
        "version": 1,
        "suppressions": {k: counts[k] for k in sorted(counts)},
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=False)
        f.write("\n")
    os.replace(tmp, path)
    return dict(counts)


def split(findings: List[Finding], baseline: Dict[str, int]
          ) -> Tuple[List[Finding], List[Finding], Dict[str, int]]:
    """-> (new, suppressed, stale) against the baseline counts.

    Occurrences of a fingerprint beyond its baselined count are *new*
    (the ones with the highest line numbers — later additions — are the
    ones reported). ``stale`` maps fingerprints whose baseline count
    exceeds reality — debt that was paid down; ``--baseline-update``
    clears it.
    """
    by_fp: Dict[str, List[Finding]] = collections.defaultdict(list)
    for f in findings:
        by_fp[f.fingerprint()].append(f)
    new: List[Finding] = []
    suppressed: List[Finding] = []
    stale: Dict[str, int] = {}
    for fp, group in by_fp.items():
        allowed = baseline.get(fp, 0)
        group.sort(key=lambda f: f.line)
        suppressed.extend(group[:allowed])
        new.extend(group[allowed:])
        if allowed > len(group):
            stale[fp] = allowed - len(group)
    for fp, count in baseline.items():
        if fp not in by_fp:
            stale[fp] = count
    new.sort(key=lambda f: (f.path, f.line))
    return new, suppressed, stale
