"""jax-purity: host syncs and nondeterminism inside traced step functions.

A ``@jax.jit``/``pjit`` body runs at *trace* time: ``.item()`` /
``np.asarray`` / ``block_until_ready`` force a device→host sync (or a
ConcretizationError), ``time.time``/``random`` bake one trace-time value
into the compiled program forever, and a Python ``if`` on a traced value
can't be staged at all. On the MFU-gap arc the step path is exactly where
an accidental host sync costs the most — a single ``.item()`` inside the
fused train step serializes every dispatch behind a device round-trip.

Detection: functions decorated with ``jax.jit``/``jit``/``pjit`` (bare,
called, or via ``partial(jax.jit, ...)``) plus module-level
``f = jax.jit(g)`` rebinds. Flags inside those bodies (nested helpers
included — they inline into the same trace):

  - host syncs: ``.item()``, ``.block_until_ready()``, ``np.asarray``,
    ``np.array``, ``jax.device_get``, ``float()``/``int()`` casts;
  - nondeterminism: ``time.time``/``perf_counter``, stdlib ``random.*``,
    ``np.random.*`` (use ``jax.random`` with explicit keys);
  - (warning) ``print`` — runs once at trace time; use
    ``jax.debug.print``;
  - (warning) a Python ``if``/``while`` testing a *parameter* of the
    jitted function — a tracer there raises at trace time; hoist to
    ``lax.cond``/``jnp.where`` or mark the arg static.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from ray_tpu.analysis.core import (
    Checker,
    Finding,
    ModuleInfo,
    call_name,
    dotted_name,
    register,
)

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit", "pjit.pjit"}
_PARTIAL_NAMES = {"partial", "functools.partial"}

_HOST_SYNC_CALLS = {"np.asarray", "np.array", "numpy.asarray",
                    "numpy.array", "jax.device_get", "onp.asarray",
                    "float", "int", "bool"}
_HOST_SYNC_METHODS = {"item", "block_until_ready", "tolist"}
_NONDET_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
                 "time.time_ns"}
_NONDET_PREFIXES = ("random.", "np.random.", "numpy.random.")


def _decorator_static_args(dec: ast.AST) -> Set[str]:
    """static_argnames from a jit decorator call, when spelled literally."""
    if not isinstance(dec, ast.Call):
        return set()
    out: Set[str] = set()
    for kw in dec.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.add(e.value)
    return out


def _is_jit_decorator(dec: ast.AST) -> bool:
    name = dotted_name(dec)
    if name in _JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        cname = call_name(dec)
        if cname in _JIT_NAMES:
            return True
        if cname in _PARTIAL_NAMES and dec.args:
            return dotted_name(dec.args[0]) in _JIT_NAMES
    return False


def _jitted_functions(mod: ModuleInfo) -> List[ast.AST]:
    jitted: List[ast.AST] = []
    by_name = {}
    for qual, fn in mod.functions():
        by_name.setdefault(fn.name, fn)
        if any(_is_jit_decorator(d) for d in fn.decorator_list):
            jitted.append(fn)
    # f = jax.jit(g) rebinds (module or function scope)
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            cname = call_name(node.value)
            if cname in _JIT_NAMES and node.value.args:
                target = node.value.args[0]
                if isinstance(target, ast.Name) and target.id in by_name:
                    jitted.append(by_name[target.id])
    return jitted


@register
class JaxPurity(Checker):
    name = "jax-purity"
    description = ("host syncs (.item/np.asarray/block_until_ready), "
                   "nondeterminism (time/random) and Python control flow "
                   "on tracers inside jit/pjit-traced functions")

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        seen: Set[int] = set()
        for fn in _jitted_functions(mod):
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            yield from self._check_traced(mod, fn)

    def _check_traced(self, mod: ModuleInfo, fn: ast.AST
                      ) -> Iterable[Finding]:
        qual = mod.qualnames().get(fn, fn.name)
        static = set()
        for dec in fn.decorator_list:
            static |= _decorator_static_args(dec)
        params = {a.arg for a in fn.args.args + fn.args.kwonlyargs
                  if a.arg not in ("self", "cls")} - static

        for node in ast.walk(fn):
            line = getattr(node, "lineno", fn.lineno)
            if mod.allowed(line, self.name):
                continue
            if isinstance(node, ast.Call):
                cname = call_name(node)
                mname = node.func.attr if isinstance(node.func,
                                                     ast.Attribute) else None
                if cname in _HOST_SYNC_CALLS:
                    # float()/int() over literals/len() is static python —
                    # only flag casts applied to a traced parameter
                    if cname in ("float", "int", "bool"):
                        if not (node.args and self._mentions(node.args[0],
                                                             params)):
                            continue
                    yield Finding(
                        checker=self.name, path=mod.relpath, line=line,
                        message=(f"{cname}() inside traced {qual!r} forces "
                                 f"a device->host sync (or fails to "
                                 f"trace)"),
                        hint="keep values on-device (jnp), or move the "
                             "readback outside the jitted step",
                        scope=qual, detail=f"host-sync:{cname}")
                elif mname in _HOST_SYNC_METHODS:
                    yield Finding(
                        checker=self.name, path=mod.relpath, line=line,
                        message=(f".{mname}() inside traced {qual!r} "
                                 f"forces a device->host sync"),
                        hint="return the array and read it back outside "
                             "the traced step",
                        scope=qual, detail=f"host-sync:.{mname}")
                elif cname in _NONDET_CALLS or (
                        cname and cname.startswith(_NONDET_PREFIXES)):
                    yield Finding(
                        checker=self.name, path=mod.relpath, line=line,
                        message=(f"{cname}() inside traced {qual!r} is "
                                 f"baked in at trace time — the compiled "
                                 f"program replays one stale value"),
                        hint="pass times in as arguments; use jax.random "
                             "with explicit keys for randomness",
                        scope=qual, detail=f"nondet:{cname}")
                elif cname == "print":
                    yield Finding(
                        checker=self.name, path=mod.relpath, line=line,
                        severity="warning",
                        message=(f"print() inside traced {qual!r} runs "
                                 f"once at trace time, not per step"),
                        hint="use jax.debug.print", scope=qual,
                        detail="print")
            elif isinstance(node, (ast.If, ast.While)):
                hit = self._tracer_test(node.test, params)
                if hit:
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield Finding(
                        checker=self.name, path=mod.relpath, line=line,
                        severity="warning",
                        message=(f"Python `{kind}` on parameter {hit!r} of "
                                 f"traced {qual!r} — a tracer here raises "
                                 f"at trace time"),
                        hint="use lax.cond/jnp.where, or mark the arg in "
                             "static_argnames",
                        scope=qual, detail=f"tracer-{kind}:{hit}")

    @staticmethod
    def _mentions(node: ast.AST, params: Set[str]) -> Optional[str]:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in params:
                return sub.id
        return None

    def _tracer_test(self, test: ast.AST, params: Set[str]
                     ) -> Optional[str]:
        """Conservative: a bare param, or a numeric comparison with a param
        on either side. `is`/`is not`/isinstance/`len()` tests are static
        structure checks and stay legal."""
        if isinstance(test, ast.Name) and test.id in params:
            return test.id
        if isinstance(test, ast.Compare):
            if any(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                   for op in test.ops):
                return None
            for side in [test.left] + list(test.comparators):
                if isinstance(side, ast.Name) and side.id in params:
                    return side.id
        return None
