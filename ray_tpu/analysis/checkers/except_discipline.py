"""except-discipline: handlers that swallow cancellation or Ctrl-C.

The PR 2 stream-pump leak was one of these: a pump loop's broad handler
ate ``asyncio.CancelledError``, so ``close()`` cancelling the pump turned
into "stream ended normally" and the consumer waited forever. In Python
3.8+ ``CancelledError`` and ``KeyboardInterrupt`` derive from
``BaseException`` precisely so ``except Exception`` *can't* swallow them
— so the rule targets the handlers that still can:

  - bare ``except:`` (anywhere — it has no legitimate spelling here),
  - ``except BaseException`` / ``except KeyboardInterrupt`` /
    ``except ...CancelledError`` **without re-raise**, but only in code
    where swallowing wedges something: ``async def`` bodies and
    long-running loops (``while True``-style pumps, typically thread
    targets).

Sanctioned shapes that do NOT fire:

  - the handler re-raises (bare ``raise`` anywhere in its body);
  - an earlier ``except CancelledError: ...raise`` sibling already
    peeled cancellation off (the replica-pump idiom);
  - the ``try`` body is a single ``await`` reaping a task that was just
    ``.cancel()``-ed (the standard child-teardown idiom).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from ray_tpu.analysis.core import (
    Checker,
    Finding,
    ModuleInfo,
    dotted_name,
    register,
)

_CANCELLED = {"CancelledError", "asyncio.CancelledError",
              "futures.CancelledError",
              "concurrent.futures.CancelledError"}
_SWALLOWS_CANCEL = _CANCELLED | {"BaseException", "KeyboardInterrupt"}


def _handler_types(handler: ast.ExceptHandler) -> List[str]:
    t = handler.type
    if t is None:
        return []
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    return [dotted_name(e) or "?" for e in elts]


def _reraises(handler: ast.ExceptHandler) -> bool:
    """Only a genuine re-raise counts: bare ``raise`` or ``raise e`` of
    the bound name. ``raise Other(...) from e`` *converts* cancellation
    into an application error — exactly the bug class — and a raise
    inside a nested def doesn't run in the handler at all."""
    stack = list(handler.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(node, ast.Raise):
            if node.exc is None:
                return True
            if isinstance(node.exc, ast.Name) and handler.name \
                    and node.exc.id == handler.name:
                return True
        stack.extend(ast.iter_child_nodes(node))
    return False


def _single_await_try(try_node: ast.Try) -> bool:
    """try body is one statement that awaits something (child-reap idiom:
    ``task.cancel(); try: await task except CancelledError: pass``)."""
    if len(try_node.body) != 1:
        return False
    stmt = try_node.body[0]
    return isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Await)


def _earlier_cancel_reraise(try_node: ast.Try,
                            handler: ast.ExceptHandler) -> bool:
    for h in try_node.handlers:
        if h is handler:
            return False
        if any(t in _CANCELLED for t in _handler_types(h)) and _reraises(h):
            return True
    return False


def _enclosing_context(mod: ModuleInfo, node: ast.AST) -> Optional[str]:
    """'async' / 'loop' when the handler sits where swallowing wedges:
    an async def, or inside a ``while True``-style pump loop."""
    parents = mod.parents()
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.While) and isinstance(cur.test,
                                                     ast.Constant) \
                and cur.test.value:
            return "loop"
        if isinstance(cur, ast.AsyncFunctionDef):
            return "async"
        if isinstance(cur, ast.FunctionDef):
            return None  # sync one-shot scope: broad capture is idiomatic
        cur = parents.get(cur)
    return None


@register
class ExceptDiscipline(Checker):
    name = "except-discipline"
    description = ("bare except, and BaseException/KeyboardInterrupt/"
                   "CancelledError swallowed without re-raise in async or "
                   "pump-loop code")

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                types = _handler_types(handler)
                line = handler.lineno
                if mod.allowed(line, self.name):
                    continue
                scope = mod.scope_of(handler)
                if handler.type is None:
                    yield Finding(
                        checker=self.name, path=mod.relpath, line=line,
                        message="bare `except:` swallows CancelledError "
                                "and KeyboardInterrupt",
                        hint="catch Exception (or name the types); "
                             "re-raise BaseException if you must touch it",
                        scope=scope, detail="bare-except")
                    continue
                bad = [t for t in types if t in _SWALLOWS_CANCEL]
                if not bad or _reraises(handler):
                    continue
                if _single_await_try(node) \
                        or _earlier_cancel_reraise(node, handler):
                    continue
                ctx = _enclosing_context(mod, handler)
                if ctx is None:
                    continue
                where = "async code" if ctx == "async" else \
                    "a long-running loop"
                yield Finding(
                    checker=self.name, path=mod.relpath, line=line,
                    message=(f"`except {', '.join(bad)}` without re-raise "
                             f"in {where} — cancellation/Ctrl-C becomes a "
                             f"swallowed error and the consumer wedges "
                             f"(the PR 2 stream-pump leak class)"),
                    hint="peel CancelledError off first and `raise`, or "
                         "re-raise after cleanup",
                    scope=scope, detail=f"swallow:{','.join(sorted(bad))}")
