"""hot-path: per-call overhead in modules on the dispatch hot path.

Some modules sit on the per-task / per-token critical path: a function-
local ``import`` there is a dict lookup + lock round-trip *per dispatch*
(PR 7 measured and hoisted a batch of these from the raylet's dispatch
loop), a per-call ``re.compile`` re-parses the pattern every request,
and constructing a fresh metric object per call defeats the registry.

Hot modules are declared two ways: the curated list below (the paths the
profiler keeps showing) and a ``# rt: hot-module`` comment in the file
itself — new hot modules self-declare without touching the checker.

Deliberate lazy imports (import-cycle breaks, heavy optional deps on
cold paths) carry ``# rt: lint-allow(hot-path) <why>``; undecided legacy
sits in the baseline where the ratchet keeps it visible.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ray_tpu.analysis.core import (
    Checker,
    Finding,
    ModuleInfo,
    call_name,
    in_type_checking_block,
    register,
)

#: modules on the per-task / per-token critical path
HOT_MODULES = {
    "ray_tpu/cluster/raylet.py",
    "ray_tpu/cluster/worker_core.py",
    "ray_tpu/models/serving.py",
    "ray_tpu/serve/handle.py",
    "ray_tpu/serve/proxy.py",
    "ray_tpu/serve/replica.py",
}

#: constructing one of these per call defeats the metrics registry;
#: ``M.get_or_create(...)`` is the sanctioned per-call idiom and is not
#: flagged.
_METRIC_CTORS = {"M.Gauge", "M.Counter", "M.Histogram",
                 "metrics.Gauge", "metrics.Counter", "metrics.Histogram"}

_REGEX_CTORS = {"re.compile"}


def _in_function(mod: ModuleInfo, node: ast.AST) -> bool:
    parents = mod.parents()
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return True
        cur = parents.get(cur)
    return False


@register
class HotPath(Checker):
    name = "hot-path"
    description = ("function-local imports and per-call re.compile / "
                   "metric construction in declared-hot modules")

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        if not (mod.hot or mod.relpath in HOT_MODULES):
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                if not _in_function(mod, node) \
                        or in_type_checking_block(mod, node) \
                        or mod.allowed(node.lineno, self.name):
                    continue
                if isinstance(node, ast.ImportFrom):
                    what = f"from {node.module or '.'} import " + \
                        ", ".join(a.name for a in node.names)
                    target = node.module or "."
                else:
                    what = "import " + ", ".join(a.name for a in node.names)
                    target = node.names[0].name
                yield Finding(
                    checker=self.name, path=mod.relpath, line=node.lineno,
                    message=(f"function-local `{what}` in hot module "
                             f"(sys.modules lookup + import lock per call)"),
                    hint="hoist to module level; if it breaks an import "
                         "cycle, say so with "
                         "`# rt: lint-allow(hot-path) <why>`",
                    scope=mod.scope_of(node), detail=f"import:{target}")
            elif isinstance(node, ast.Call):
                cname = call_name(node)
                if cname in _REGEX_CTORS:
                    kind, hint = "re.compile", "compile once at module level"
                elif cname in _METRIC_CTORS:
                    kind, hint = cname, \
                        "use M.get_or_create (the registry idiom) or " \
                        "hoist the instrument to module/init scope"
                else:
                    continue
                if not _in_function(mod, node) \
                        or mod.allowed(node.lineno, self.name):
                    continue
                yield Finding(
                    checker=self.name, path=mod.relpath, line=node.lineno,
                    message=(f"per-call {kind}(...) in hot module — "
                             f"constructed on every invocation"),
                    hint=hint, scope=mod.scope_of(node),
                    detail=f"ctor:{cname}")
