"""guarded-by: declared lock invariants, checked at every mutation site.

The lightweight Python twin of ``ABSL_GUARDED_BY``: annotate a shared
mutable attribute where it is initialized —

    self._entries = {}   # rt: guarded-by(_lock)

— and the checker flags any *mutation* of ``self._entries`` (assignment,
augmented assignment, subscript store, or a mutating method call like
``.append``/``.pop``/``.update``) that is not lexically inside
``with self._lock:``. Helper methods whose names end in ``_locked``
are assumed to be called with the lock held (the repo's existing idiom:
``_evict_locked``, ``_drain_derefs_locked``); ``__init__`` and
``_init_*`` constructor-extension helpers (the recorder-core idiom:
``_init_core``, called from subclass ``__init__`` before any concurrent
alias exists) are exempt. Reads are deliberately not checked — too
noisy to enforce mechanically, and the writes are where lost-update
races live.

A declaration whose named lock doesn't exist on the class is itself a
finding: annotations must not rot. The lock may live on a base class —
in-module bases are resolved transitively; when a base is imported from
another module the attribute set is unknowable here, so the stale
warning is suppressed rather than guessed (mutation checks still run:
they only need the declaration, not the lock's home).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Optional, Set, Tuple

from ray_tpu.analysis.core import (
    Checker,
    Finding,
    ModuleInfo,
    register,
)

_MUTATORS = {
    "append", "appendleft", "add", "clear", "discard", "extend", "insert",
    "pop", "popitem", "popleft", "remove", "reverse", "rotate", "sort",
    "setdefault", "update",
}


def _walk_skip_nested_classes(cls: ast.ClassDef):
    """Walk a class body without descending into nested ClassDefs (a
    nested class runs the whole check for itself — attributing its
    declarations to the outer class would cross-wire the two)."""
    stack: list = list(ast.iter_child_nodes(cls))
    while stack:
        node = stack.pop()
        if isinstance(node, ast.ClassDef):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _class_decls(mod: ModuleInfo, cls: ast.ClassDef
                 ) -> Dict[str, Tuple[str, int]]:
    """attr -> (lockname, decl_line) from ``# rt: guarded-by`` comments
    attached to ``self.attr = ...`` (methods) or ``attr = ...`` /
    ``attr: T = ...`` (class body) lines."""
    decls: Dict[str, Tuple[str, int]] = {}
    for node in _walk_skip_nested_classes(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        lock = mod.guarded.get(node.lineno)
        if not lock:
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for tgt in targets:
            attr = _self_attr(tgt)
            if attr is None and isinstance(tgt, ast.Name):
                attr = tgt.id
            if attr:
                decls[attr] = (lock, node.lineno)
    return decls


def _class_attrs(cls: ast.ClassDef) -> Set[str]:
    out: Set[str] = set()
    for node in _walk_skip_nested_classes(cls):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                attr = _self_attr(tgt)
                if attr:
                    out.add(attr)
                elif isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def _attrs_with_bases(cls: ast.ClassDef,
                      by_name: Dict[str, ast.ClassDef],
                      seen: Set[str]) -> Tuple[Set[str], bool]:
    """Attributes assigned on ``cls`` plus any base class resolvable in
    this module (transitively). The second element is False when a base
    is imported / not resolvable here — the attribute set is then a
    lower bound and "the lock doesn't exist" cannot be proven."""
    attrs = _class_attrs(cls)
    complete = True
    for b in cls.bases:
        if isinstance(b, ast.Name):
            if b.id == "object":
                continue
            base = by_name.get(b.id)
            if base is None:
                complete = False
            elif base.name not in seen:
                seen.add(base.name)
                battrs, bcomplete = _attrs_with_bases(base, by_name, seen)
                attrs |= battrs
                complete = complete and bcomplete
        else:
            # ast.Attribute (module.Base), Subscript (Generic[T]), ...
            complete = False
    return attrs, complete


def _under_lock(mod: ModuleInfo, node: ast.AST, lock: str,
                method: ast.AST) -> bool:
    """Is ``node`` lexically inside ``with self.<lock>:`` within
    ``method``?"""
    parents = mod.parents()
    cur = parents.get(node)
    while cur is not None and cur is not method:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                ce = item.context_expr
                if _self_attr(ce) == lock or (
                        isinstance(ce, ast.Name) and ce.id == lock):
                    return True
        cur = parents.get(cur)
    return False


@register
class GuardedBy(Checker):
    name = "guarded-by"
    description = ("mutations of `# rt: guarded-by(_lock)`-annotated "
                   "attributes outside `with self._lock:`")

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        if not mod.guarded:
            return
        qn = mod.qualnames()
        by_name = {node.name: node for node in qn
                   if isinstance(node, ast.ClassDef)}
        for cls_node, cls_qual in list(qn.items()):
            if not isinstance(cls_node, ast.ClassDef):
                continue
            decls = _class_decls(mod, cls_node)
            if not decls:
                continue
            attrs, complete = _attrs_with_bases(cls_node, by_name,
                                                {cls_node.name})
            for attr, (lock, decl_line) in decls.items():
                if complete and lock not in attrs:
                    yield Finding(
                        checker=self.name, path=mod.relpath,
                        line=decl_line, severity="warning",
                        message=(f"guarded-by({lock}) on {cls_qual}."
                                 f"{attr}: the class has no attribute "
                                 f"{lock!r} — stale annotation"),
                        hint="point the annotation at the real lock (or "
                             "delete it)",
                        scope=f"{cls_qual}.{attr}",
                        detail=f"stale:{attr}->{lock}")
            # direct methods only: a nested class re-runs this loop itself
            for method in cls_node.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                if method.name == "__init__" \
                        or method.name.startswith("_init") \
                        or method.name.endswith("_locked"):
                    continue
                yield from self._check_method(mod, cls_qual, method, decls)

    def _check_method(self, mod: ModuleInfo, cls_qual: str, method: ast.AST,
                      decls: Dict[str, Tuple[str, int]]
                      ) -> Iterable[Finding]:
        mqual = f"{cls_qual}.{method.name}"
        for node in ast.walk(method):
            attr: Optional[str] = None
            how = ""
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    a = _self_attr(tgt)
                    if a is None and isinstance(tgt, ast.Subscript):
                        a = _self_attr(tgt.value)
                        if a in decls:
                            attr, how = a, "subscript store on"
                    elif a in decls:
                        attr, how = a, "assignment to"
            elif isinstance(node, ast.Delete):
                for tgt in node.targets:
                    a = _self_attr(tgt) or (
                        _self_attr(tgt.value)
                        if isinstance(tgt, ast.Subscript) else None)
                    if a in decls:
                        attr, how = a, "del on"
            elif isinstance(node, ast.Call) and isinstance(node.func,
                                                           ast.Attribute):
                if node.func.attr in _MUTATORS:
                    a = _self_attr(node.func.value)
                    if a in decls:
                        attr, how = a, f".{node.func.attr}() on"
            if attr is None:
                continue
            lock, _ = decls[attr]
            line = node.lineno
            if mod.allowed(line, self.name) \
                    or _under_lock(mod, node, lock, method):
                continue
            yield Finding(
                checker=self.name, path=mod.relpath, line=line,
                message=(f"{how} self.{attr} outside `with self.{lock}:` "
                         f"(declared guarded-by({lock}))"),
                hint=f"take self.{lock}, rename the method *_locked if "
                     f"it is only called under the lock, or annotate the "
                     f"line `# rt: lint-allow(guarded-by) <why>`",
                scope=mqual, detail=f"{attr}@{method.name}")
