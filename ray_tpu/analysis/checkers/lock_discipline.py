"""lock-discipline: locks and the contexts that must never take them.

Two invariants, both paid for in blood:

1. **No lock acquisition reachable from GC/finalizer/signal context.**
   ``weakref.finalize`` callbacks (and ``__del__``, and signal handlers)
   can run on *any* thread at *any* allocation — including a thread
   already inside the lock they'd take. The PR 8 object-ledger deadlock
   was exactly this: ``_deref`` (a finalizer) took ``_lock`` while the
   cyclic GC fired it on a thread mid-``_entry()``, wedging every
   ``ObjectRef.__init__`` in the serve proxy for 10+ minutes. The rule
   walks an intra-module call graph so a finalizer that *calls into* a
   lock-taking helper is caught too.

2. **No blocking call while holding a lock.** ``ray_tpu.get`` /
   ``time.sleep`` / subprocess / socket-dial under ``with self._lock``
   turns every other acquirer into a convoy behind one slow RPC (the
   serve controller used to boot proxy actors under the lock its status
   getters share). ``await`` under a *threading* lock in an async def is
   the same bug with the event loop as the victim.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu.analysis.core import (
    BLOCKING_CALLS,
    Checker,
    Finding,
    ModuleInfo,
    call_name,
    dotted_name,
    register,
)

_LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore", "Lock", "RLock",
    "Condition",
}

#: dotted call names that block the calling thread (shared table — the
#: event-loop checker flags the same set inside async defs)
_BLOCKING_CALLS = set(BLOCKING_CALLS)

_MAX_CALL_DEPTH = 6


def _is_lockish(name: str, known: Set[str]) -> bool:
    return name in known or "lock" in name.lower() or "_cv" in name


def _lock_expr_name(expr: ast.AST, known: Set[str]) -> Optional[str]:
    """Lock name if ``expr`` is ``self.X``/``X`` and X looks like a lock."""
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id in ("self", "cls"):
        return expr.attr if _is_lockish(expr.attr, known) else None
    if isinstance(expr, ast.Name):
        return expr.id if _is_lockish(expr.id, known) else None
    return None


def _body_walk_no_defs(nodes: Iterable[ast.AST]) -> Iterable[ast.AST]:
    """Walk statements without descending into nested def/class bodies
    (code in a nested def does not run while the lock is held)."""
    stack = list(nodes)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _known_locks(mod: ModuleInfo) -> Set[str]:
    """Names assigned from a lock factory anywhere in the module."""
    known: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            cname = call_name(node.value)
            if cname in _LOCK_FACTORIES or (
                    cname and cname.split(".")[-1] in ("Lock", "RLock",
                                                       "Condition")):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute):
                        known.add(tgt.attr)
                    elif isinstance(tgt, ast.Name):
                        known.add(tgt.id)
    return known


def _acquired_locks(fn: ast.AST, known: Set[str]) -> List[Tuple[str, int]]:
    """(lock_name, line) for every acquisition lexically in ``fn``."""
    out: List[Tuple[str, int]] = []
    for node in _body_walk_no_defs(getattr(fn, "body", ())):
        if isinstance(node, ast.With):
            for item in node.items:
                name = _lock_expr_name(item.context_expr, known)
                if name:
                    out.append((name, node.lineno))
        elif isinstance(node, ast.Call) and isinstance(node.func,
                                                       ast.Attribute) \
                and node.func.attr == "acquire":
            name = _lock_expr_name(node.func.value, known)
            if name:
                out.append((name, node.lineno))
    return out


def _callee_qualname(call: ast.Call, caller_qual: str) -> Optional[str]:
    """Resolve ``self.m()`` / ``m()`` to an intra-module qualname."""
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id in ("self", "cls"):
        cls = caller_qual.rsplit(".", 1)[0] if "." in caller_qual else ""
        return f"{cls}.{f.attr}" if cls else f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _callback_qualname(cb: ast.AST, site_scope: str) -> Optional[str]:
    """Qualname a callback expression (``self._m`` / ``m``) points at."""
    if isinstance(cb, ast.Attribute) and isinstance(cb.value, ast.Name) \
            and cb.value.id in ("self", "cls"):
        cls = site_scope.rsplit(".", 1)[0] if "." in site_scope else ""
        return f"{cls}.{cb.attr}" if cls else cb.attr
    if isinstance(cb, ast.Name):
        return cb.id
    return None


@register
class LockDiscipline(Checker):
    name = "lock-discipline"
    description = ("locks acquired from finalizer/__del__/signal context; "
                   "blocking calls (RPC, get, sleep, await) under a held "
                   "lock")

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        known = _known_locks(mod)
        acquires: Dict[str, List[Tuple[str, int]]] = {}
        calls: Dict[str, Set[str]] = {}
        fn_lines: Dict[str, int] = {}
        for qual, fn in mod.functions():
            acquires[qual] = _acquired_locks(fn, known)
            fn_lines[qual] = fn.lineno
            callees: Set[str] = set()
            for node in _body_walk_no_defs(fn.body):
                if isinstance(node, ast.Call):
                    callee = _callee_qualname(node, qual)
                    if callee:
                        callees.add(callee)
            calls[qual] = callees

        yield from self._finalizer_rule(mod, known, acquires, calls,
                                        fn_lines)
        yield from self._held_across_blocking_rule(mod, known)

    # -- rule 1: finalizer/GC/signal contexts ---------------------------------
    def _finalizer_rule(self, mod, known, acquires, calls, fn_lines):
        roots: List[Tuple[str, int, str]] = []  # (qualname, line, context)
        for qual, fn in mod.functions():
            if qual.split(".")[-1] == "__del__":
                roots.append((qual, fn.lineno, "__del__"))
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node)
            if cname in ("weakref.finalize", "finalize") \
                    and len(node.args) >= 2:
                cb = _callback_qualname(node.args[1], mod.scope_of(node))
                if cb:
                    roots.append((cb, node.lineno, "weakref.finalize"))
            elif cname in ("signal.signal",) and len(node.args) >= 2:
                cb = _callback_qualname(node.args[1], mod.scope_of(node))
                if cb:
                    roots.append((cb, node.lineno, "signal handler"))

        for root, line, context in roots:
            hit = self._reaches_lock(root, acquires, calls)
            if hit is None or mod.allowed(line, self.name):
                continue
            lock, path = hit
            via = "" if len(path) == 1 else \
                f" via {' -> '.join(path[1:])}"
            yield Finding(
                checker=self.name, path=mod.relpath, line=line,
                message=(f"{context} callback {root!r} acquires lock "
                         f"{lock!r}{via} — GC/finalizer context can run on "
                         f"a thread already holding it (self-deadlock)"),
                hint="only touch atomic structures (deque.append) in "
                     "finalizers; drain the backlog inside the next locked "
                     "operation",
                scope=root, detail=f"{context}->{lock}")

    @staticmethod
    def _reaches_lock(root: str, acquires, calls
                      ) -> Optional[Tuple[str, List[str]]]:
        seen: Set[str] = set()
        stack: List[Tuple[str, List[str]]] = [(root, [root])]
        while stack:
            qual, path = stack.pop()
            if qual in seen or len(path) > _MAX_CALL_DEPTH:
                continue
            seen.add(qual)
            got = acquires.get(qual)
            if got:
                return got[0][0], path
            for callee in calls.get(qual, ()):
                if callee in acquires:  # known intra-module function
                    stack.append((callee, path + [callee]))
        return None

    # -- rule 2: blocking call / await under a held lock ----------------------
    def _held_across_blocking_rule(self, mod: ModuleInfo, known: Set[str]
                                   ) -> Iterable[Finding]:
        for qual, fn in mod.functions():
            is_async = isinstance(fn, ast.AsyncFunctionDef)
            for node in _body_walk_no_defs(fn.body):
                if not isinstance(node, ast.With):
                    continue
                locks = [n for item in node.items
                         if (n := _lock_expr_name(item.context_expr,
                                                  known))]
                if not locks:
                    continue
                lock = locks[0]
                for sub in _body_walk_no_defs(node.body):
                    if isinstance(sub, ast.Await) and is_async:
                        if mod.allowed(sub.lineno, self.name):
                            continue
                        yield Finding(
                            checker=self.name, path=mod.relpath,
                            line=sub.lineno,
                            message=(f"await while holding threading lock "
                                     f"{lock!r} — blocks the event loop's "
                                     f"other tasks behind this lock"),
                            hint="use asyncio.Lock, or release before "
                                 "awaiting",
                            scope=qual, detail=f"{lock}@await")
                    elif isinstance(sub, ast.Call):
                        cname = call_name(sub)
                        if cname not in _BLOCKING_CALLS:
                            continue
                        if mod.allowed(sub.lineno, self.name):
                            continue
                        yield Finding(
                            checker=self.name, path=mod.relpath,
                            line=sub.lineno,
                            message=(f"blocking call {cname}() while "
                                     f"holding lock {lock!r} — every other "
                                     f"acquirer convoys behind it"),
                            hint="move the blocking work outside the lock; "
                                 "re-take it to publish the result",
                            scope=qual, detail=f"{lock}@{cname}")
