"""event-loop-blocking: no synchronous stalls inside ``async def``.

A blocking call in a coroutine freezes every task sharing the loop — in
the serve plane that's the proxy (all in-flight HTTP requests), the
handle router, and the replica pump; in the control plane it's the
raylet/GCS RPC servers. The rule flags known thread-blockers inside
``async def`` bodies: ``time.sleep``, blocking ``ray_tpu.get``/``wait``,
subprocess calls, synchronous sockets/HTTP, and (as a warning)
synchronous file ``open`` — small local files usually survive review,
but they belong in an executor on hot paths.

Nested ``def``s inside the coroutine are skipped: they typically run in
executors (``run_in_executor(None, fn)``), not on the loop.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ray_tpu.analysis.core import (
    BLOCKING_CALLS,
    Checker,
    Finding,
    ModuleInfo,
    call_name,
    register,
)
from ray_tpu.analysis.checkers.lock_discipline import _body_walk_no_defs

_BLOCKING = BLOCKING_CALLS  # shared with lock-discipline: one definition
# of "blocking", two contexts (under a held lock / on the event loop)

_WARN_ONLY = {
    "open": "loop.run_in_executor for file IO on hot paths",
}


@register
class EventLoopBlocking(Checker):
    name = "event-loop-blocking"
    description = ("time.sleep / blocking get / sync subprocess / sync "
                   "file+socket IO inside async def bodies")

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        for qual, fn in mod.functions():
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in _body_walk_no_defs(fn.body):
                if not isinstance(node, ast.Call):
                    continue
                cname = call_name(node)
                if cname is None:
                    continue
                if cname in _BLOCKING:
                    severity, hint = "error", _BLOCKING[cname]
                elif cname in _WARN_ONLY:
                    severity, hint = "warning", _WARN_ONLY[cname]
                else:
                    continue
                if mod.allowed(node.lineno, self.name):
                    continue
                yield Finding(
                    checker=self.name, path=mod.relpath, line=node.lineno,
                    severity=severity,
                    message=(f"{cname}() inside async def {qual!r} blocks "
                             f"the event loop (every task on this loop "
                             f"stalls with it)"),
                    hint=f"use {hint}",
                    scope=qual, detail=cname)
