"""metrics-doc: every registered ``rt_*`` series documented, no drift.

The PR 4 metrics-doc lint (``scripts/check_metrics.py``), folded into the
framework as a cross-file checker — the script survives as a thin shim
over this module so ``python scripts/check_metrics.py`` and the tier-1
``tests/test_zz_metrics_doc.py`` keep working unchanged.

Checks (unchanged semantics):

  1. scan ``ray_tpu/**/*.py`` for ``M.get_or_create(M.<Kind>, "rt_...")``
     registrations + the dashboard's ``SYSTEM_METRICS`` table;
  2. no name under conflicting kinds (sharing a name with the same kind
     is the one-series-many-processes idiom);
  3. every name documented in README's "Metrics reference" table with the
     matching kind; no stale rows;
  4. every ``rt_*`` series a generated Grafana panel queries is
     registered;
  5. ``scripts/alert_rules.yml`` is structurally sound and references
     only registered series.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Tuple

from ray_tpu.analysis.core import (
    Checker,
    Finding,
    ModuleInfo,
    REPO_ROOT,
    register,
)

_GET_OR_CREATE = re.compile(
    r"get_or_create\(\s*M\.(Counter|Gauge|Histogram)\s*,\s*"
    r"\"(rt_[a-z0-9_]+)\"", re.S)
_SYSTEM_ROW = re.compile(
    r"\"(rt_[a-z0-9_]+)\":\s*\(\"(gauge|counter|histogram)\"")
_README_ROW = re.compile(
    r"^\|\s*`(rt_[a-z0-9_]+)`\s*\|\s*(counter|gauge|histogram)\s*\|", re.M)
_METRIC_NAME = re.compile(r"\b(rt_[a-z0-9_]+)")


def registered_metrics(root: str = REPO_ROOT
                       ) -> Dict[str, List[Tuple[str, str]]]:
    """name -> [(kind, relpath), ...] across every registration site."""
    regs: Dict[str, List[Tuple[str, str]]] = {}
    pkg = os.path.join(root, "ray_tpu")
    for dirpath, _, files in os.walk(pkg):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            try:
                with open(path, encoding="utf-8") as f:
                    src = f.read()
            except OSError:
                continue
            for kind, name in _GET_OR_CREATE.findall(src):
                regs.setdefault(name, []).append((kind.lower(), rel))
            if "SYSTEM_METRICS" in src:
                for name, kind in _SYSTEM_ROW.findall(src):
                    regs.setdefault(name, []).append((kind, rel))
    return regs


def documented_metrics(root: str = REPO_ROOT) -> Dict[str, str]:
    with open(os.path.join(root, "README.md"), encoding="utf-8") as f:
        readme = f.read()
    return {name: kind for name, kind in _README_ROW.findall(readme)}


def _base_names(expr: str) -> List[str]:
    """rt_* metric names in a PromQL expression, histogram exposition
    suffixes stripped back to the registered base."""
    out = []
    for name in _METRIC_NAME.findall(expr):
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                name = name[:-len(suffix)]
                break
        out.append(name)
    return out


def grafana_expr_metrics(root: str = REPO_ROOT) -> List[Tuple[str, str]]:
    """(metric_name, panel_title) for every rt_* series the generated
    Grafana dashboard queries (loaded standalone by file path — the
    module only imports stdlib at top level)."""
    import importlib.util

    path = os.path.join(root, "ray_tpu", "dashboard", "grafana.py")
    spec = importlib.util.spec_from_file_location("_rt_grafana_lint", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out: List[Tuple[str, str]] = []
    for panel in mod.build_cluster_dashboard()["panels"]:
        for target in panel.get("targets", ()):
            for name in _base_names(target.get("expr", "")):
                out.append((name, panel.get("title", "?")))
    return out


def alert_rules_problems(regs: Dict[str, List[Tuple[str, str]]],
                         root: str = REPO_ROOT) -> List[str]:
    """Structural + metric-name lint of scripts/alert_rules.yml."""
    path = os.path.join(root, "scripts", "alert_rules.yml")
    if not os.path.exists(path):
        return ["scripts/alert_rules.yml missing (the failure-plane "
                "alerting rules ship with the repo)"]
    with open(path, encoding="utf-8") as f:
        text = f.read()
    problems: List[str] = []
    try:
        import yaml

        doc = yaml.safe_load(text)
        groups = (doc or {}).get("groups")
        if not isinstance(groups, list) or not groups:
            return [f"{path}: no alerting groups defined"]
        exprs: List[Tuple[str, str]] = []
        for g in groups:
            rules = (g or {}).get("rules")
            if not isinstance(rules, list) or not rules:
                problems.append(f"{path}: group {g.get('name')!r} has no "
                                f"rules")
                continue
            for r in rules:
                if not r.get("alert") or not r.get("expr"):
                    problems.append(f"{path}: rule {r.get('alert')!r} "
                                    f"needs both 'alert' and 'expr'")
                    continue
                exprs.append((str(r["expr"]), str(r["alert"])))
    except ImportError:
        # no pyyaml: degrade to a regex scan so the metric-name lint
        # still runs (structure unchecked)
        exprs = [(m, "alert_rules.yml")
                 for m in re.findall(r"expr:\s*(.+)", text)]
        if "groups:" not in text or "rules:" not in text:
            problems.append(f"{path}: missing groups:/rules: structure")
    except Exception as e:  # noqa: BLE001 — malformed YAML IS the finding
        return [f"{path}: does not parse as YAML ({type(e).__name__}: "
                f"{e})"]
    for expr, alert in exprs:
        for name in _base_names(expr):
            if name not in regs:
                problems.append(
                    f"{path}: alert {alert!r} references {name}, which "
                    f"is not a registered metric")
    return problems


def check(root: str = REPO_ROOT) -> List[str]:
    """Every problem as one message string (the shim/test API)."""
    problems: List[str] = []
    regs = registered_metrics(root)
    if not regs:
        return ["no rt_* metric registrations found — the scanner regexes "
                "no longer match the registration idiom"]
    docs = documented_metrics(root)
    if not docs:
        problems.append("README.md has no 'Metrics reference' table rows "
                        "(| `rt_name` | kind | description |)")
    for name, sites in sorted(regs.items()):
        kinds = {k for k, _ in sites}
        if len(kinds) > 1:
            problems.append(
                f"{name}: registered under conflicting kinds "
                f"{sorted(kinds)} at {sorted(p for _, p in sites)}")
            continue
        kind = next(iter(kinds))
        if name not in docs:
            problems.append(
                f"{name} ({kind}, {sites[0][1]}): not documented in "
                f"README.md's metrics table")
        elif docs[name] != kind:
            problems.append(
                f"{name}: registered as {kind} ({sites[0][1]}) but "
                f"documented as {docs[name]}")
    for name in sorted(set(docs) - set(regs)):
        problems.append(f"{name}: documented in README.md but never "
                        f"registered in ray_tpu/ (stale row?)")
    try:
        for name, title in grafana_expr_metrics(root):
            if name not in regs:
                problems.append(
                    f"grafana panel {title!r} queries {name}, which is "
                    f"not a registered metric")
    except Exception as e:  # noqa: BLE001 — a broken factory IS a finding
        problems.append(f"grafana dashboard factory failed to load: "
                        f"{type(e).__name__}: {e}")
    problems.extend(alert_rules_problems(regs, root))
    return problems


@register
class MetricsDoc(Checker):
    name = "metrics-doc"
    description = ("registered rt_* series vs README metrics table, "
                   "Grafana panels, and alert rules (PR 4 lint, folded in)")

    def finalize(self, mods: List[ModuleInfo], root: str
                 ) -> List[Finding]:
        # repo-level check: runs off the tree, not the scanned file set
        return [
            Finding(checker=self.name, path="README.md", line=1,
                    message=problem,
                    hint="python scripts/check_metrics.py for the "
                         "standalone view",
                    scope="metrics", detail=problem)
            for problem in check(root)
        ]
