"""Bundled checkers. Importing this package registers every checker with
the framework registry (``core.all_checkers`` does it for you)."""

from ray_tpu.analysis.checkers import (  # noqa: F401
    event_loop,
    except_discipline,
    guarded_by,
    hot_path,
    jax_purity,
    lock_discipline,
    metrics_doc,
)
