"""Job submission.

Reference analog: ``dashboard/modules/job/`` — ``JobManager``
(``job_manager.py:517``, ``submit_job :832``), ``JobSupervisor`` (``:140``,
a detached actor running the entrypoint subprocess and capturing logs),
``sdk.py`` ``JobSubmissionClient``, ``cli.py`` (``ray job submit/...``).
Redesign: no dashboard REST hop — the client attaches as a driver and talks
to the supervisor actor directly; job metadata lives in the GCS KV.
"""

from ray_tpu.job.job_manager import (  # noqa: F401
    JobSubmissionClient,
    job_status,
    list_jobs,
    stop_job,
    submit_job,
    tail_job_logs,
)
