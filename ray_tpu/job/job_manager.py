"""JobSupervisor actor + submission client.

Reference analog: ``dashboard/modules/job/job_manager.py`` — the supervisor
is a detached actor that owns the entrypoint subprocess (``JobSupervisor
:140``), so the job outlives the submitting client; status/log access goes
through the actor; metadata persists in the GCS KV under ``@jobs/``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import uuid
from typing import Any, Dict, List, Optional

_NAMESPACE = "_rt_job"
_KV_PREFIX = "@jobs/"

# Terminal states match the reference's JobStatus enum.
PENDING, RUNNING, SUCCEEDED, FAILED, STOPPED = (
    "PENDING", "RUNNING", "SUCCEEDED", "FAILED", "STOPPED")


class _JobSupervisor:
    """Runs one entrypoint subprocess; detached so it survives the client."""

    def __init__(self, job_id: str, entrypoint: str, env_vars: Dict[str, str],
                 gcs_address: str, log_dir: str):
        self.job_id = job_id
        self.entrypoint = entrypoint
        os.makedirs(log_dir, exist_ok=True)
        self.log_path = os.path.join(log_dir, f"job-{job_id}.log")
        env = dict(os.environ)
        env.update(env_vars or {})
        env["RT_JOB_ID"] = job_id
        env["RT_ADDRESS"] = gcs_address  # job script: init(address="auto")
        self._log_file = open(self.log_path, "ab")
        self._proc = subprocess.Popen(
            entrypoint, shell=True, env=env,
            stdout=self._log_file, stderr=subprocess.STDOUT,
            start_new_session=True)
        self._stopped = False
        self._update(RUNNING)

    def _update(self, status: str, rc: Optional[int] = None) -> None:
        import ray_tpu

        meta = {"job_id": self.job_id, "entrypoint": self.entrypoint,
                "status": status, "log_path": self.log_path,
                "return_code": rc, "updated_at": time.time()}
        ray_tpu.global_worker()._require_backend().kv_put(
            _KV_PREFIX + self.job_id, json.dumps(meta).encode())

    def poll(self) -> str:
        """Refresh + return status (called by clients; also finalizes)."""
        rc = self._proc.poll()
        if rc is None:
            return RUNNING
        status = (STOPPED if self._stopped
                  else SUCCEEDED if rc == 0 else FAILED)
        self._update(status, rc)
        return status

    def logs(self, offset: int = 0, max_bytes: int = 1 << 20) -> Dict[str, Any]:
        # poll BEFORE reading: if the process exits between a read and the
        # poll, done=True would drop the tail written in that window
        done = self._proc.poll() is not None
        self._log_file.flush()
        try:
            with open(self.log_path, "rb") as f:
                f.seek(offset)
                data = f.read(max_bytes)
        except FileNotFoundError:
            data = b""
        return {"data": data.decode(errors="replace"),
                "next_offset": offset + len(data),
                "done": done}

    def stop(self) -> bool:
        if self._proc.poll() is None:
            self._stopped = True
            try:
                os.killpg(os.getpgid(self._proc.pid), 15)
            except (ProcessLookupError, PermissionError):
                self._proc.terminate()
            return True
        return False


def _backend():
    import ray_tpu

    return ray_tpu.global_worker()._require_backend()


def submit_job(entrypoint: str, *, env_vars: Optional[Dict[str, str]] = None,
               job_id: Optional[str] = None) -> str:
    """Start ``entrypoint`` under a detached supervisor actor; returns the
    job id immediately (reference: ``JobManager.submit_job``)."""
    import ray_tpu
    from ray_tpu._private.config import get_config

    job_id = job_id or f"job_{uuid.uuid4().hex[:10]}"
    backend = _backend()
    log_dir = os.path.join(get_config().session_dir_root,
                           backend.session_name, "logs")
    backend.kv_put(_KV_PREFIX + job_id, json.dumps({
        "job_id": job_id, "entrypoint": entrypoint, "status": PENDING,
        "updated_at": time.time()}).encode())
    ray_tpu.remote(num_cpus=0)(_JobSupervisor).options(
        name=f"job:{job_id}", namespace=_NAMESPACE,
        lifetime="detached").remote(
        job_id, entrypoint, env_vars or {}, backend.gcs_address, log_dir)
    return job_id


def _supervisor(job_id: str):
    import ray_tpu

    return ray_tpu.get_actor(f"job:{job_id}", namespace=_NAMESPACE)


def job_status(job_id: str) -> Dict[str, Any]:
    import ray_tpu

    try:
        status = ray_tpu.get(_supervisor(job_id).poll.remote(), timeout=30)
    except Exception:
        status = None  # supervisor gone: fall back to the KV record
    raw = _backend().kv_get(_KV_PREFIX + job_id)
    if raw is None:
        raise ValueError(f"no such job: {job_id}")
    meta = json.loads(raw)
    if status is not None:
        meta["status"] = status
    return meta


def tail_job_logs(job_id: str, offset: int = 0) -> Dict[str, Any]:
    import ray_tpu

    return ray_tpu.get(_supervisor(job_id).logs.remote(offset), timeout=30)


def stop_job(job_id: str) -> bool:
    import ray_tpu

    return ray_tpu.get(_supervisor(job_id).stop.remote(), timeout=30)


def list_jobs() -> List[Dict[str, Any]]:
    backend = _backend()
    out = []
    for key in backend.kv_keys(_KV_PREFIX):
        raw = backend.kv_get(key)
        if raw:
            out.append(json.loads(raw))
    return sorted(out, key=lambda m: m.get("updated_at", 0))


class JobSubmissionClient:
    """SDK shape parity with the reference's ``JobSubmissionClient``."""

    def __init__(self, address: Optional[str] = None):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address or "auto")

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[Dict] = None,
                   job_id: Optional[str] = None) -> str:
        env_vars = (runtime_env or {}).get("env_vars")
        return submit_job(entrypoint, env_vars=env_vars, job_id=job_id)

    def get_job_status(self, job_id: str) -> str:
        return job_status(job_id)["status"]

    def get_job_logs(self, job_id: str) -> str:
        return tail_job_logs(job_id)["data"]

    def stop_job(self, job_id: str) -> bool:
        return stop_job(job_id)

    def list_jobs(self) -> List[Dict[str, Any]]:
        return list_jobs()
