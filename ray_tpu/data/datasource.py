"""Datasources: lazy read tasks and file writers.

Reference analog: ``data/datasource/`` (parquet/csv/json/numpy readers with
path expansion + per-file read tasks) and ``Dataset.write_*``. A ReadTask
is a zero-arg callable returning one block; reads execute remotely, one
task per file/fragment, so a Dataset over many files is read in parallel
and streamed.
"""

from __future__ import annotations

import glob as globlib
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.data import block as B

ReadTask = Callable[[], B.Block]


def expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if not f.startswith(".")))
        elif any(c in p for c in "*?["):
            out.extend(sorted(globlib.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def range_read_tasks(n: int, num_blocks: int) -> List[ReadTask]:
    num_blocks = max(1, min(num_blocks, n)) if n else 1
    per = (n + num_blocks - 1) // num_blocks if num_blocks else 0
    tasks = []
    for i in range(num_blocks):
        lo, hi = i * per, min((i + 1) * per, n)
        if lo >= hi and n > 0:
            continue

        def read(lo=lo, hi=hi) -> B.Block:
            return {"id": np.arange(lo, hi, dtype=np.int64)}

        tasks.append(read)
    return tasks or [lambda: {"id": np.arange(0, dtype=np.int64)}]


def parquet_read_tasks(paths, columns: Optional[List[str]] = None) -> List[ReadTask]:
    files = expand_paths(paths)

    def make(path):
        def _table_to_block(table) -> B.Block:
            return {name: np.asarray(table.column(name).to_pylist())
                    if table.column(name).type.__class__.__name__ == "ListType"
                    else table.column(name).to_numpy(zero_copy_only=False)
                    for name in table.column_names}

        def read():
            """Generator: one block per row group — the streaming read task
            turns each into its own ref so downstream stages overlap with
            the file read (reference: streamed read outputs in Data)."""
            import pyarrow.parquet as pq

            f = pq.ParquetFile(path)
            if f.num_row_groups <= 1:
                yield _table_to_block(f.read(columns=columns))
                return
            for rg in range(f.num_row_groups):
                yield _table_to_block(f.read_row_group(rg, columns=columns))

        return read

    return [make(p) for p in files]


def csv_read_tasks(paths, **pandas_kwargs) -> List[ReadTask]:
    files = expand_paths(paths)

    def make(path):
        def read() -> B.Block:
            import pandas as pd

            return B.from_pandas(pd.read_csv(path, **pandas_kwargs))

        return read

    return [make(p) for p in files]


def json_read_tasks(paths, lines: bool = True) -> List[ReadTask]:
    files = expand_paths(paths)

    def make(path):
        def read() -> B.Block:
            import pandas as pd

            return B.from_pandas(pd.read_json(path, lines=lines))

        return read

    return [make(p) for p in files]


def numpy_read_tasks(paths, column: str = "data") -> List[ReadTask]:
    files = expand_paths(paths)

    def make(path):
        def read() -> B.Block:
            return {column: np.load(path)}

        return read

    return [make(p) for p in files]


def text_read_tasks(paths, drop_empty: bool = True) -> List[ReadTask]:
    """One row per line (reference: ``read_text``)."""
    files = expand_paths(paths)

    def make(path):
        def read() -> B.Block:
            with open(path, "r", errors="replace") as f:
                lines = [ln.rstrip("\n") for ln in f]
            if drop_empty:
                lines = [ln for ln in lines if ln]
            return {"text": np.asarray(lines, dtype=object)}

        return read

    return [make(p) for p in files]


def binary_read_tasks(paths, include_paths: bool = False) -> List[ReadTask]:
    """One row per file with raw bytes (reference: ``read_binary_files`` —
    the substrate image/webdataset readers decode from)."""
    files = expand_paths(paths)

    def make(path):
        def read() -> B.Block:
            with open(path, "rb") as f:
                data = f.read()
            block: B.Block = {"bytes": np.asarray([data], dtype=object)}
            if include_paths:
                block["path"] = np.asarray([path], dtype=object)
            return block

        return read

    return [make(p) for p in files]


def sql_read_tasks(sql: str, connection_factory) -> List[ReadTask]:
    """Rows from a DB-API connection (reference: ``read_sql``); the factory
    runs IN the read task so connections are per-worker."""

    def read() -> B.Block:
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            names = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            conn.close()
        cols: dict = {n: [] for n in names}
        for row in rows:
            for n, v in zip(names, row):
                cols[n].append(v)
        return {n: np.asarray(v) for n, v in cols.items()}

    return [read]


def images_read_tasks(paths, size=None, mode: str = "RGB") -> List[ReadTask]:
    """Decoded image arrays, one row per file (reference: ``read_images``).
    Requires PIL; raises a clear error when absent."""
    files = expand_paths(paths)

    def make(path):
        def read() -> B.Block:
            try:
                from PIL import Image
            except ImportError as e:  # pragma: no cover - env-dependent
                raise ImportError(
                    "read_images requires pillow (PIL)") from e
            img = Image.open(path).convert(mode)
            if size is not None:
                img = img.resize(tuple(size))
                image_col = np.asarray(img)[None, ...]
            else:
                # variable-size images can't share a dense [N,H,W,C] column
                # (block concat needs matching trailing dims) — store each
                # as an object cell, like read_binary_files
                image_col = np.empty(1, dtype=object)
                image_col[0] = np.asarray(img)
            return {"image": image_col,
                    "path": np.asarray([path], dtype=object)}

        return read

    return [make(p) for p in files]


# ---- writers (run as remote tasks, one file per block) ----


def write_block(block: B.Block, path: str, file_format: str, index: int) -> str:
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{index:05d}.{file_format}")
    if file_format == "parquet":
        import pyarrow as pa
        import pyarrow.parquet as pq

        pq.write_table(pa.table({k: list(v) if v.ndim > 1 else v
                                 for k, v in block.items()}), out)
    elif file_format == "csv":
        B.to_pandas(block).to_csv(out, index=False)
    elif file_format == "json":
        B.to_pandas(block).to_json(out, orient="records", lines=True)
    elif file_format == "npy":
        if len(block) != 1:
            raise ValueError("write_numpy requires a single-column dataset")
        np.save(out, next(iter(block.values())))
    else:
        raise ValueError(f"unsupported format {file_format}")
    return out
