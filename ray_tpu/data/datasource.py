"""Datasources: lazy read tasks and file writers.

Reference analog: ``data/datasource/`` (parquet/csv/json/numpy readers with
path expansion + per-file read tasks) and ``Dataset.write_*``. A ReadTask
is a zero-arg callable returning one block; reads execute remotely, one
task per file/fragment, so a Dataset over many files is read in parallel
and streamed.
"""

from __future__ import annotations

import glob as globlib
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.data import block as B

ReadTask = Callable[[], B.Block]


def expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if not f.startswith(".")))
        elif any(c in p for c in "*?["):
            out.extend(sorted(globlib.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def range_read_tasks(n: int, num_blocks: int) -> List[ReadTask]:
    num_blocks = max(1, min(num_blocks, n)) if n else 1
    per = (n + num_blocks - 1) // num_blocks if num_blocks else 0
    tasks = []
    for i in range(num_blocks):
        lo, hi = i * per, min((i + 1) * per, n)
        if lo >= hi and n > 0:
            continue

        def read(lo=lo, hi=hi) -> B.Block:
            return {"id": np.arange(lo, hi, dtype=np.int64)}

        tasks.append(read)
    return tasks or [lambda: {"id": np.arange(0, dtype=np.int64)}]


def parquet_read_tasks(paths, columns: Optional[List[str]] = None) -> List[ReadTask]:
    files = expand_paths(paths)

    def make(path):
        def _table_to_block(table) -> B.Block:
            return {name: np.asarray(table.column(name).to_pylist())
                    if table.column(name).type.__class__.__name__ == "ListType"
                    else table.column(name).to_numpy(zero_copy_only=False)
                    for name in table.column_names}

        def read():
            """Generator: one block per row group — the streaming read task
            turns each into its own ref so downstream stages overlap with
            the file read (reference: streamed read outputs in Data)."""
            import pyarrow.parquet as pq

            f = pq.ParquetFile(path)
            if f.num_row_groups <= 1:
                yield _table_to_block(f.read(columns=columns))
                return
            for rg in range(f.num_row_groups):
                yield _table_to_block(f.read_row_group(rg, columns=columns))

        # tags the optimizer's projection-pushdown rule rewrites by
        # (optimizer.py:_rewrite_parquet_columns)
        read.parquet_path = path
        read.parquet_columns = list(columns) if columns else None
        return read

    return [make(p) for p in files]


def csv_read_tasks(paths, **pandas_kwargs) -> List[ReadTask]:
    files = expand_paths(paths)

    def make(path):
        def read() -> B.Block:
            import pandas as pd

            return B.from_pandas(pd.read_csv(path, **pandas_kwargs))

        return read

    return [make(p) for p in files]


def json_read_tasks(paths, lines: bool = True) -> List[ReadTask]:
    files = expand_paths(paths)

    def make(path):
        def read() -> B.Block:
            import pandas as pd

            return B.from_pandas(pd.read_json(path, lines=lines))

        return read

    return [make(p) for p in files]


def numpy_read_tasks(paths, column: str = "data") -> List[ReadTask]:
    files = expand_paths(paths)

    def make(path):
        def read() -> B.Block:
            return {column: np.load(path)}

        return read

    return [make(p) for p in files]


def text_read_tasks(paths, drop_empty: bool = True) -> List[ReadTask]:
    """One row per line (reference: ``read_text``)."""
    files = expand_paths(paths)

    def make(path):
        def read() -> B.Block:
            with open(path, "r", errors="replace") as f:
                lines = [ln.rstrip("\n") for ln in f]
            if drop_empty:
                lines = [ln for ln in lines if ln]
            return {"text": np.asarray(lines, dtype=object)}

        return read

    return [make(p) for p in files]


def binary_read_tasks(paths, include_paths: bool = False) -> List[ReadTask]:
    """One row per file with raw bytes (reference: ``read_binary_files`` —
    the substrate image/webdataset readers decode from)."""
    files = expand_paths(paths)

    def make(path):
        def read() -> B.Block:
            with open(path, "rb") as f:
                data = f.read()
            block: B.Block = {"bytes": np.asarray([data], dtype=object)}
            if include_paths:
                block["path"] = np.asarray([path], dtype=object)
            return block

        return read

    return [make(p) for p in files]


def sql_read_tasks(sql: str, connection_factory) -> List[ReadTask]:
    """Rows from a DB-API connection (reference: ``read_sql``); the factory
    runs IN the read task so connections are per-worker."""

    def read() -> B.Block:
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            names = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            conn.close()
        cols: dict = {n: [] for n in names}
        for row in rows:
            for n, v in zip(names, row):
                cols[n].append(v)
        return {n: np.asarray(v) for n, v in cols.items()}

    return [read]


def images_read_tasks(paths, size=None, mode: str = "RGB") -> List[ReadTask]:
    """Decoded image arrays, one row per file (reference: ``read_images``).
    Requires PIL; raises a clear error when absent."""
    files = expand_paths(paths)

    def make(path):
        def read() -> B.Block:
            try:
                from PIL import Image
            except ImportError as e:  # pragma: no cover - env-dependent
                raise ImportError(
                    "read_images requires pillow (PIL)") from e
            img = Image.open(path).convert(mode)
            if size is not None:
                img = img.resize(tuple(size))
                image_col = np.asarray(img)[None, ...]
            else:
                # variable-size images can't share a dense [N,H,W,C] column
                # (block concat needs matching trailing dims) — store each
                # as an object cell, like read_binary_files
                image_col = np.empty(1, dtype=object)
                image_col[0] = np.asarray(img)
            return {"image": image_col,
                    "path": np.asarray([path], dtype=object)}

        return read

    return [make(p) for p in files]


# ---- writers (run as remote tasks, one file per block) ----


def write_block(block: B.Block, path: str, file_format: str, index: int) -> str:
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{index:05d}.{file_format}")
    if file_format == "parquet":
        import pyarrow as pa
        import pyarrow.parquet as pq

        pq.write_table(pa.table({k: list(v) if v.ndim > 1 else v
                                 for k, v in block.items()}), out)
    elif file_format == "csv":
        B.to_pandas(block).to_csv(out, index=False)
    elif file_format == "json":
        B.to_pandas(block).to_json(out, orient="records", lines=True)
    elif file_format == "npy":
        if len(block) != 1:
            raise ValueError("write_numpy requires a single-column dataset")
        np.save(out, next(iter(block.values())))
    elif file_format == "tfrecords":
        return write_tfrecords_block(block, path, index)
    else:
        raise ValueError(f"unsupported format {file_format}")
    return out


# ---- TFRecord (reference: data/datasource/tfrecords_datasource.py) ---------
#
# TFRecord framing: u64-LE length | u32 masked-crc(length) | payload |
# u32 masked-crc(payload). Payloads are tf.train.Example protos; the tiny
# wire-format codec below handles exactly that schema (BytesList /
# FloatList / Int64List feature maps) with no tensorflow dependency.

def _read_varint(buf: memoryview, pos: int):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _iter_proto_fields(buf: memoryview):
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 2:  # length-delimited
            n, pos = _read_varint(buf, pos)
            yield field, buf[pos:pos + n]
            pos += n
        elif wire == 0:  # varint
            v, pos = _read_varint(buf, pos)
            yield field, v
        elif wire == 5:  # 32-bit
            yield field, bytes(buf[pos:pos + 4])
            pos += 4
        elif wire == 1:  # 64-bit
            yield field, bytes(buf[pos:pos + 8])
            pos += 8
        else:
            raise ValueError(f"unsupported proto wire type {wire}")


def _decode_example(payload: memoryview) -> Dict[str, Any]:
    import struct

    out: Dict[str, Any] = {}
    for f, features in _iter_proto_fields(payload):
        if f != 1:  # Example.features
            continue
        for f2, entry in _iter_proto_fields(features):
            if f2 != 1:  # Features.feature map entry
                continue
            key, feature = None, None
            for f3, v in _iter_proto_fields(entry):
                if f3 == 1:
                    key = bytes(v).decode()
                elif f3 == 2:
                    feature = v
            if key is None or feature is None:
                continue
            for kind, body in _iter_proto_fields(feature):
                vals: List[Any] = []
                if kind == 1:  # BytesList
                    vals = [bytes(v) for f4, v in _iter_proto_fields(body)
                            if f4 == 1]
                elif kind == 2:  # FloatList (packed or repeated)
                    for f4, v in _iter_proto_fields(body):
                        if isinstance(v, (bytes, memoryview)) and len(v) % 4 == 0 and not isinstance(v, int):
                            vals.extend(struct.unpack(f"<{len(v)//4}f", v))
                        else:
                            vals.append(struct.unpack("<f", v)[0])
                elif kind == 3:  # Int64List (packed varints or repeated)
                    for f4, v in _iter_proto_fields(body):
                        if isinstance(v, int):
                            vals.append(v)
                        else:
                            pos = 0
                            mv = memoryview(v)
                            while pos < len(mv):
                                x, pos = _read_varint(mv, pos)
                                if x >= 1 << 63:  # two's-complement int64
                                    x -= 1 << 64
                                vals.append(x)
                out[key] = vals[0] if len(vals) == 1 else vals
    return out


def _tfrecord_frames(path: str):
    import struct

    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                return
            (length,) = struct.unpack("<Q", header[:8])
            payload = f.read(length)
            f.read(4)  # payload crc (masked crc32c) — tolerated, not checked
            if len(payload) < length:
                return  # torn tail
            yield payload


def tfrecords_read_tasks(paths) -> List[ReadTask]:
    files = expand_paths(paths)

    def make(path):
        def read() -> B.Block:
            rows = [_decode_example(memoryview(p))
                    for p in _tfrecord_frames(path)]
            return B.from_rows(rows)

        return read

    return [make(p) for p in files]


def _masked_crc(data: bytes) -> int:
    from ray_tpu import _native

    crc = _native.crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


def _encode_example(row: Dict[str, Any]) -> bytes:
    """Encode one row as tf.train.Example (inverse of _decode_example)."""
    import struct

    def varint(n: int) -> bytes:
        # proto int64: negatives are 10-byte two's-complement varints — the
        # unsigned mask also stops `n >>= 7` looping forever on n < 0
        n &= (1 << 64) - 1
        out = b""
        while True:
            b7 = n & 0x7F
            n >>= 7
            if n:
                out += bytes([b7 | 0x80])
            else:
                return out + bytes([b7])

    def ld(field: int, body: bytes) -> bytes:  # length-delimited field
        return varint(field << 3 | 2) + varint(len(body)) + body

    feats = b""
    for key, value in row.items():
        vals = value if isinstance(value, (list, tuple, np.ndarray)) else [value]
        first = vals[0] if len(vals) else 0
        if isinstance(first, (bytes, str)):
            body = b"".join(
                ld(1, v.encode() if isinstance(v, str) else bytes(v))
                for v in vals)
            feature = ld(1, body)
        elif isinstance(first, (float, np.floating)):
            packed = struct.pack(f"<{len(vals)}f", *[float(v) for v in vals])
            feature = ld(2, ld(1, packed))
        else:
            packed = b"".join(varint(int(v)) for v in vals)
            feature = ld(3, ld(1, packed))
        feats += ld(1, ld(1, key.encode()) + ld(2, feature))
    return ld(1, feats)


def write_tfrecords_block(block: B.Block, path: str, index: int) -> str:
    import struct

    out = os.path.join(path, f"part-{index:05d}.tfrecords")
    with open(out, "wb") as f:
        for row in B.iter_rows(block):
            payload = _encode_example(row)
            header = struct.pack("<Q", len(payload))
            f.write(header + struct.pack("<I", _masked_crc(header))
                    + payload + struct.pack("<I", _masked_crc(payload)))
    return out


# ---- WebDataset (reference: data/datasource/webdataset_datasource.py) ------

def _wds_decode(ext: str, payload: bytes) -> Any:
    ext = ext.lower()
    if ext in ("jpg", "jpeg", "png", "ppm", "bmp"):
        import io

        from PIL import Image

        return np.asarray(Image.open(io.BytesIO(payload)).convert("RGB"))
    if ext == "json":
        import json

        return json.loads(payload)
    if ext in ("txt", "text"):
        return payload.decode()
    if ext in ("cls", "id", "index"):
        return int(payload.decode().strip())
    if ext == "npy":
        import io

        return np.load(io.BytesIO(payload), allow_pickle=False)
    return payload  # unknown extension: raw bytes


def webdataset_read_tasks(paths, *, decode: bool = True) -> List[ReadTask]:
    """One read task per .tar shard; samples are files grouped by the
    basename up to the first dot, columns keyed by extension."""
    files = expand_paths(paths)

    def make(path):
        def read() -> B.Block:
            import tarfile

            samples: Dict[str, Dict[str, Any]] = {}
            order: List[str] = []
            with tarfile.open(path) as tar:
                for member in tar:
                    if not member.isfile():
                        continue
                    base = os.path.basename(member.name)
                    if "." in base:
                        key, ext = base.split(".", 1)
                    else:
                        key, ext = base, ""
                    payload = tar.extractfile(member).read()
                    if key not in samples:
                        samples[key] = {"__key__": key}
                        order.append(key)
                    samples[key][ext] = (_wds_decode(ext, payload)
                                         if decode else payload)
            return B.from_rows([samples[k] for k in order])

        return read

    return [make(p) for p in files]
