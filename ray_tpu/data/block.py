"""Blocks: the unit of distributed data.

Reference analog: ``python/ray/data/block.py`` (``Block``/``BlockMetadata``/
``BlockAccessor``). The native format here is **columnar numpy** — a dict of
equal-length ``np.ndarray`` columns — because that is what feeds ``jnp``
device puts without conversion (the reference's native format is Arrow for
the same zero-copy reason on CPU).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np

# A block is a dict of equal-length numpy columns.
Block = Dict[str, np.ndarray]


@dataclasses.dataclass
class BlockMetadata:
    num_rows: int
    size_bytes: int
    schema: Optional[Dict[str, str]] = None


def _to_array(values: List[Any]) -> np.ndarray:
    arr = np.asarray(values)
    if arr.dtype == object and values and isinstance(values[0], str):
        arr = np.asarray(values, dtype=np.str_)
    return arr


def from_rows(rows: List[Dict[str, Any]]) -> Block:
    if not rows:
        return {}
    cols = {}
    for key in rows[0]:
        cols[key] = _to_array([r[key] for r in rows])
    return cols


def from_items(items: List[Any]) -> Block:
    if items and isinstance(items[0], dict):
        return from_rows(items)
    return {"item": _to_array(items)}


def num_rows(block: Block) -> int:
    if not block:
        return 0
    return len(next(iter(block.values())))


def size_bytes(block: Block) -> int:
    return sum(int(getattr(c, "nbytes", 0)) for c in block.values())


def metadata(block: Block) -> BlockMetadata:
    return BlockMetadata(
        num_rows=num_rows(block), size_bytes=size_bytes(block),
        schema={k: str(v.dtype) for k, v in block.items()})


def slice_block(block: Block, start: int, end: int) -> Block:
    return {k: v[start:end] for k, v in block.items()}


def take_rows(block: Block, indices: np.ndarray) -> Block:
    return {k: v[indices] for k, v in block.items()}


def concat(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if num_rows(b) > 0]
    if not blocks:
        return {}
    keys = list(blocks[0].keys())
    return {k: np.concatenate([b[k] for b in blocks]) for k in keys}


def _row_value(v: Any) -> Any:
    # object-dtype columns (text/bytes) index to plain python values
    shape = getattr(v, "shape", None)
    return v.item() if shape == () else v


def iter_rows(block: Block) -> Iterator[Dict[str, Any]]:
    n = num_rows(block)
    keys = list(block.keys())
    for i in range(n):
        yield {k: _row_value(block[k][i]) for k in keys}


def to_pandas(block: Block):
    import pandas as pd

    return pd.DataFrame({k: list(v) if v.ndim > 1 else v
                         for k, v in block.items()})


def from_pandas(df) -> Block:
    return {str(c): df[c].to_numpy() for c in df.columns}


def to_batch(block: Block, batch_format: str):
    if batch_format in ("numpy", "default"):
        return dict(block)
    if batch_format == "pandas":
        return to_pandas(block)
    raise ValueError(f"unsupported batch_format {batch_format!r}")


def from_batch(batch: Union[Block, "Any"]) -> Block:
    if isinstance(batch, dict):
        return {k: np.asarray(v) for k, v in batch.items()}
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return from_pandas(batch)
    except ImportError:
        pass
    raise TypeError(
        f"map_batches UDF must return a dict of arrays or a DataFrame, "
        f"got {type(batch)}")
