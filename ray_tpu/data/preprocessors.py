"""Preprocessors: fit-on-Dataset, transform-as-map_batches.

Reference analog: ``python/ray/data/preprocessors/`` (``Preprocessor`` base
``preprocessor.py``, scalers, encoders, imputers, ``Chain``,
``Concatenator``). Fit statistics come from the Dataset's distributed
aggregates; ``transform`` appends a fused map stage, so preprocessing
streams with the rest of the plan (and feeds ``iter_batches`` on the TPU
input path with no extra materialization).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class Preprocessor:
    """fit(ds) computes state; transform(ds) appends a map stage."""

    _fitted = False

    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def transform(self, ds):
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} must be fit first")
        fn = self._transform_batch_fn()
        return ds.map_batches(fn, batch_format="numpy")

    def transform_batch(self, batch: Dict[str, np.ndarray]) -> Dict:
        return self._transform_batch_fn()(dict(batch))

    # subclass hooks
    def _fit(self, ds) -> None:
        pass

    def _needs_fit(self) -> bool:
        return True

    def _transform_batch_fn(self):
        raise NotImplementedError


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (reference: ``StandardScaler``)."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, Any] = {}

    def _fit(self, ds) -> None:
        from ray_tpu.data.aggregate import Mean, Std

        aggs = []
        for c in self.columns:
            aggs += [Mean(c), Std(c)]
        row = ds.aggregate(*aggs)
        self.stats_ = {c: (row[f"mean({c})"], row[f"std({c})"] or 1.0)
                       for c in self.columns}

    def _transform_batch_fn(self):
        stats, cols = self.stats_, self.columns

        def tx(batch):
            for c in cols:
                mean, std = stats[c]
                batch[c] = (batch[c] - mean) / (std if std else 1.0)
            return batch

        return tx


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, Any] = {}

    def _fit(self, ds) -> None:
        from ray_tpu.data.aggregate import Max, Min

        aggs = []
        for c in self.columns:
            aggs += [Min(c), Max(c)]
        row = ds.aggregate(*aggs)
        self.stats_ = {c: (row[f"min({c})"], row[f"max({c})"])
                       for c in self.columns}

    def _transform_batch_fn(self):
        stats, cols = self.stats_, self.columns

        def tx(batch):
            for c in cols:
                lo, hi = stats[c]
                span = (hi - lo) or 1.0
                batch[c] = (batch[c] - lo) / span
            return batch

        return tx


class LabelEncoder(Preprocessor):
    """Categorical column -> dense int codes (sorted unique order)."""

    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: Optional[np.ndarray] = None

    def _fit(self, ds) -> None:
        uniques: set = set()
        for batch in ds.iter_batches(batch_format="numpy"):
            uniques.update(np.unique(batch[self.label_column]).tolist())
        self.classes_ = np.asarray(sorted(uniques))

    def _transform_batch_fn(self):
        classes, col = self.classes_, self.label_column

        def tx(batch):
            codes = np.searchsorted(classes, batch[col])
            # searchsorted gives colliding/out-of-range codes for UNSEEN
            # values — corrupt labels must be loud, not silent
            codes_clipped = np.clip(codes, 0, len(classes) - 1)
            unseen = classes[codes_clipped] != batch[col]
            if unseen.any():
                bad = np.unique(np.asarray(batch[col])[unseen])[:5]
                raise ValueError(
                    f"LabelEncoder({col!r}): values not seen during fit: "
                    f"{bad.tolist()}")
            batch[col] = codes
            return batch

        return tx


class OneHotEncoder(Preprocessor):
    """Categorical columns -> one indicator column per category."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.categories_: Dict[str, np.ndarray] = {}

    def _fit(self, ds) -> None:
        uniques: Dict[str, set] = {c: set() for c in self.columns}
        for batch in ds.iter_batches(batch_format="numpy"):
            for c in self.columns:
                uniques[c].update(np.unique(batch[c]).tolist())
        self.categories_ = {c: np.asarray(sorted(v))
                            for c, v in uniques.items()}

    def _transform_batch_fn(self):
        cats, cols = self.categories_, self.columns

        def tx(batch):
            for c in cols:
                vals = batch.pop(c)
                for cat in cats[c]:
                    batch[f"{c}_{cat}"] = (vals == cat).astype(np.int64)
            return batch

        return tx


class SimpleImputer(Preprocessor):
    """Fill NaNs with the column mean (or a constant)."""

    def __init__(self, columns: List[str], strategy: str = "mean",
                 fill_value: Optional[float] = None):
        if strategy not in ("mean", "constant"):
            raise ValueError(f"unknown strategy {strategy!r}")
        if strategy == "constant" and fill_value is None:
            raise ValueError(
                "strategy='constant' requires fill_value (None would "
                "silently re-fill NaNs with NaN)")
        self.columns = columns
        self.strategy = strategy
        self.fill_value = fill_value
        self.stats_: Dict[str, float] = {}

    def _needs_fit(self) -> bool:
        return self.strategy == "mean"

    def _fit(self, ds) -> None:
        if self.strategy != "mean":
            return
        sums = {c: 0.0 for c in self.columns}
        counts = {c: 0 for c in self.columns}
        for batch in ds.iter_batches(batch_format="numpy"):
            for c in self.columns:
                v = batch[c].astype(np.float64)
                ok = ~np.isnan(v)
                sums[c] += float(v[ok].sum())
                counts[c] += int(ok.sum())
        self.stats_ = {c: (sums[c] / counts[c]) if counts[c] else 0.0
                       for c in self.columns}

    def _transform_batch_fn(self):
        cols = self.columns
        fills = (self.stats_ if self.strategy == "mean"
                 else {c: self.fill_value for c in cols})

        def tx(batch):
            for c in cols:
                v = batch[c].astype(np.float64)
                v[np.isnan(v)] = fills[c]
                batch[c] = v
            return batch

        return tx


class Concatenator(Preprocessor):
    """Merge numeric columns into one feature vector column."""

    def __init__(self, columns: List[str], output_column_name: str = "features",
                 dtype=np.float32):
        self.columns = columns
        self.output_column_name = output_column_name
        self.dtype = dtype

    def _needs_fit(self) -> bool:
        return False

    def _transform_batch_fn(self):
        cols, out, dtype = self.columns, self.output_column_name, self.dtype

        def tx(batch):
            parts = []
            for c in cols:
                v = batch.pop(c)
                parts.append(v.reshape(len(v), -1).astype(dtype))
            batch[out] = np.concatenate(parts, axis=1)
            return batch

        return tx


class Chain(Preprocessor):
    """Apply preprocessors in sequence; fit runs each on the PRE-transformed
    output of its predecessors (reference: ``Chain``)."""

    def __init__(self, *stages: Preprocessor):
        self.stages = list(stages)

    def fit(self, ds) -> "Chain":
        for st in self.stages:
            if st._needs_fit():
                st.fit(ds)
            ds = st.transform(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        for st in self.stages:
            ds = st.transform(ds)
        return ds

    def _needs_fit(self) -> bool:
        return any(st._needs_fit() for st in self.stages)
