"""DataContext: execution knobs (reference: ``data/context.py``)."""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

_local = threading.local()


@dataclasses.dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    max_tasks_in_flight: int = 8
    read_parallelism: int = 8
    eager_free: bool = True
    # Pipelined shuffle via per-partition merger actors (reference:
    # _internal/push_based_shuffle.py, Exoshuffle): map outputs stream into
    # mergers while other map tasks still run; memory per partition is
    # bounded by the incremental merge. Off by default (matches the
    # reference's RAY_DATA_PUSH_BASED_SHUFFLE gate).
    use_push_based_shuffle: bool = False

    @staticmethod
    def get_current() -> "DataContext":
        ctx = getattr(_local, "ctx", None)
        if ctx is None:
            ctx = DataContext()
            _local.ctx = ctx
        return ctx
