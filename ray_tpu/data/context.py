"""DataContext: execution knobs (reference: ``data/context.py``)."""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

_local = threading.local()


@dataclasses.dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    max_tasks_in_flight: int = 8
    read_parallelism: int = 8
    eager_free: bool = True
    # rule-based logical-plan rewrites (data/optimizer.py; reference:
    # _internal/logical/optimizers.py)
    optimizer_enabled: bool = True
    # resource-aware streaming backpressure (reference:
    # streaming_executor_state.py:55 TopologyResourceUsage): a map stage
    # stops submitting while its estimated in-flight output bytes exceed
    # this budget (0 disables; the count cap above always applies).
    memory_budget_bytes: int = 2 * 1024**3
    # CPU-aware cap: in-flight tasks per stage <= cluster CPUs x this
    # factor (0 disables; >1 keeps a submission queue so workers never
    # idle between blocks).
    cpu_oversubscription: float = 2.0
    # Pipelined shuffle via per-partition merger actors (reference:
    # _internal/push_based_shuffle.py, Exoshuffle): map outputs stream into
    # mergers while other map tasks still run; memory per partition is
    # bounded by the incremental merge. Off by default (matches the
    # reference's RAY_DATA_PUSH_BASED_SHUFFLE gate).
    use_push_based_shuffle: bool = False

    @staticmethod
    def get_current() -> "DataContext":
        ctx = getattr(_local, "ctx", None)
        if ctx is None:
            ctx = DataContext()
            _local.ctx = ctx
        return ctx
