"""Rule-based logical-plan optimizer.

Reference analog: ``data/_internal/logical/optimizers.py:1`` (the
``LogicalOptimizer`` rule list: operator fusion, limit/projection pushdown,
randomize-blocks reordering). The executor's planner already fuses runs of
map-like ops into single tasks (``executor.py:plan``); this pass runs BEFORE
planning and rewrites the (read_tasks, ops) pair itself:

  - ``projection_pushdown_into_read``: a leading ``SelectColumns`` over
    column-rewritable read tasks (parquet) becomes a column-pruned read —
    pruned columns are never decoded or shipped.
  - ``limit_pushdown``: ``Limit`` moves upstream past row-count-preserving
    ops so per-row work happens only on surviving rows; adjacent limits
    collapse to the smaller.
  - ``filter_before_shuffle``: a ``Filter`` directly after
    ``RandomShuffle``/``Repartition`` runs before it instead — dropped rows
    are never shuffled.
  - ``shuffle_elision``: a ``RandomShuffle``/``Repartition`` feeding an
    order-insensitive all-to-all (``Aggregate``, ``Sort``, another shuffle)
    is dead work and is removed.

Every rewrite is semantics-preserving on the multiset of rows (order is
only reordered where the downstream op is order-insensitive). ``optimize``
returns the applied rule names so callers/tests can assert on them.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from ray_tpu.data import logical as L

# ops that neither add, drop, nor reorder rows — Limit commutes with them
_ROW_PRESERVING = (L.MapRows, L.AddColumn, L.DropColumns, L.SelectColumns)


def _rewrite_parquet_columns(read_tasks: List,
                             columns: List[str]) -> Optional[List]:
    """Rebuild parquet read tasks with a pruned column list; None if any
    task is not column-rewritable (non-parquet or already narrower)."""
    from ray_tpu.data.datasource import parquet_read_tasks

    paths = []
    for t in read_tasks:
        path = getattr(t, "parquet_path", None)
        if path is None:
            return None
        existing = getattr(t, "parquet_columns", None)
        if existing is not None and not set(columns) <= set(existing):
            return None  # selection asks for columns the read won't have
        paths.append(path)
    return parquet_read_tasks(paths, columns=list(columns))


def optimize(read_tasks: List, ops: List[L.LogicalOp]
             ) -> Tuple[List, List[L.LogicalOp], List[str]]:
    """Apply rules to fixpoint; returns (read_tasks, ops, applied_rules)."""
    applied: List[str] = []
    ops = list(ops)

    changed = True
    while changed:
        changed = False

        # -- projection pushdown into the read ---------------------------
        if ops and isinstance(ops[0], L.SelectColumns):
            rewritten = _rewrite_parquet_columns(read_tasks, ops[0].columns)
            if rewritten is not None:
                read_tasks = rewritten
                ops.pop(0)
                applied.append("projection_pushdown_into_read")
                changed = True
                continue

        for i in range(len(ops) - 1):
            a, b = ops[i], ops[i + 1]
            # -- limit pushdown / fusion ---------------------------------
            if isinstance(b, L.Limit) and isinstance(a, _ROW_PRESERVING):
                ops[i], ops[i + 1] = b, a
                applied.append("limit_pushdown")
                changed = True
                break
            if isinstance(a, L.Limit) and isinstance(b, L.Limit):
                ops[i:i + 2] = [L.Limit(min(a.n, b.n))]
                applied.append("limit_fusion")
                changed = True
                break
            # -- filter before shuffle -----------------------------------
            if (isinstance(b, L.Filter)
                    and isinstance(a, (L.RandomShuffle, L.Repartition))):
                ops[i], ops[i + 1] = b, a
                applied.append("filter_before_shuffle")
                changed = True
                break
            # -- shuffle elision -----------------------------------------
            # a's row distribution is destroyed/recreated by b anyway —
            # EXCEPT RandomShuffle -> Repartition: repartition scatters
            # deterministically, so eliding the shuffle would silently
            # drop the pipeline's randomness
            if (isinstance(a, (L.RandomShuffle, L.Repartition))
                    and isinstance(b, (L.Aggregate, L.Sort,
                                       L.RandomShuffle, L.Repartition))
                    and not (isinstance(a, L.RandomShuffle)
                             and isinstance(b, L.Repartition))):
                ops.pop(i)
                applied.append("shuffle_elision")
                changed = True
                break

    return read_tasks, ops, applied


def explain(read_tasks: List, ops: List[L.LogicalOp]) -> str:
    """Human-readable before/after plan (``Dataset.explain()``)."""
    before = [type(o).__name__ for o in ops]
    _, out_ops, applied = optimize(read_tasks, ops)
    after = [type(o).__name__ for o in out_ops]
    lines = [f"logical plan : {' -> '.join(before) or '(scan only)'}",
             f"optimized    : {' -> '.join(after) or '(scan only)'}"]
    if applied:
        lines.append(f"applied rules: {', '.join(applied)}")
    return "\n".join(lines)
