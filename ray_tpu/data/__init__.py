"""ray_tpu.data: streaming distributed data (the reference's ``ray.data``).

Columnar-numpy blocks flow through fused map tasks with bounded in-flight
parallelism; all-to-all ops run as task-graph map/reduce; consumption
streams into batches (numpy / pandas / jnp-on-device for the TPU feed path).
"""

from ray_tpu.data.aggregate import (  # noqa: F401
    AggregateFn,
    Count,
    Max,
    Mean,
    Min,
    Quantile,
    Std,
    Sum,
)
from ray_tpu.data.context import DataContext  # noqa: F401
from ray_tpu.data.dataset import (  # noqa: F401
    Dataset,
    from_arrow,
    from_huggingface,
    from_items,
    from_numpy,
    from_pandas,
    from_torch,
    range,
    read_binary_files,
    read_csv,
    read_images,
    read_json,
    read_numpy,
    read_parquet,
    read_sql,
    read_text,
    read_tfrecords,
    read_webdataset,
)
from ray_tpu.data.iterator import DataIterator  # noqa: F401
from ray_tpu.data.logical import ActorPoolStrategy  # noqa: F401
from ray_tpu.data import preprocessors  # noqa: F401,E402
from ray_tpu.data.preprocessors import (  # noqa: F401,E402
    Chain,
    Concatenator,
    LabelEncoder,
    MinMaxScaler,
    OneHotEncoder,
    Preprocessor,
    SimpleImputer,
    StandardScaler,
)
