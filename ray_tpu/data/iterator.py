"""Consumption: batch iteration and streaming splits.

Reference analogs: ``data/_internal/block_batching/iter_batches.py``
(batching across block boundaries + prefetch), ``DataIterator``
(``data/iterator.py``), and ``streaming_split`` /
``_internal/iterator/stream_split_iterator.py`` (a coordinator actor hands
blocks to N concurrent consumers — Train workers — round-robin).
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import block as B


def batches_from_blocks(blocks: Iterator[B.Block], batch_size: Optional[int],
                        batch_format: str = "numpy", drop_last: bool = False,
                        local_shuffle_buffer_size: Optional[int] = None,
                        seed: Optional[int] = None) -> Iterator[Any]:
    """Re-chunk a stream of blocks into fixed-size batches."""
    rng = np.random.default_rng(seed)
    buf: List[B.Block] = []
    buffered = 0
    min_buffer = local_shuffle_buffer_size or 0

    def drain(final: bool) -> Iterator[Any]:
        nonlocal buf, buffered
        while buf and (batch_size is None or buffered >= batch_size
                       or (final and buffered > 0)):
            if batch_size is None:
                merged, buf, buffered = B.concat(buf), [], 0
                yield B.to_batch(merged, batch_format)
                return
            merged = B.concat(buf)
            if local_shuffle_buffer_size and B.num_rows(merged) > 1:
                merged = B.take_rows(
                    merged, rng.permutation(B.num_rows(merged)))
            take = min(batch_size, B.num_rows(merged))
            if take < batch_size and not final:
                buf, buffered = [merged], B.num_rows(merged)
                return
            if take < batch_size and drop_last:
                buf, buffered = [], 0
                return
            yield B.to_batch(B.slice_block(merged, 0, take), batch_format)
            rest = B.slice_block(merged, take, B.num_rows(merged))
            buf = [rest] if B.num_rows(rest) else []
            buffered = B.num_rows(rest)

    for blk in blocks:
        if B.num_rows(blk) == 0:
            continue
        buf.append(blk)
        buffered += B.num_rows(blk)
        if batch_size is not None and buffered >= max(batch_size, min_buffer):
            yield from drain(final=False)
    yield from drain(final=True)


def prefetched(it: Iterator[Any], depth: int) -> Iterator[Any]:
    """Run the upstream iterator in a thread, `depth` items ahead.

    The producer must not block forever when the consumer abandons the
    iterator early (``break`` mid-epoch) — a stop event unwinds it and
    releases its buffered blocks.
    """
    if depth <= 0:
        yield from it
        return
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    _END = object()
    err: List[BaseException] = []
    stop = threading.Event()

    def producer():
        try:
            for item in it:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:
            err.append(e)
        finally:
            # the END sentinel must arrive even when the queue is full —
            # keep trying unless the consumer already stopped
            while not stop.is_set():
                try:
                    q.put(_END, timeout=0.1)
                    break
                except queue.Full:
                    continue

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        stop.set()


class JaxBatchIterator:
    """Iterator of jnp device batches with ingest-vs-compute accounting.

    The time THIS iterator spends producing a batch (pipeline pull +
    host→device put) is **ingest**; the time the consumer holds the batch
    between ``next()`` calls (their train step) is **compute**.
    ``report()`` states which side gates the run — the number VERDICT asks
    for ("host-side input pipelines that keep chips fed"): a training loop
    is *ingest-limited* when the chips wait on data, *compute-limited* when
    the pipeline keeps up.

    ``stack`` advertises the K-stacking factor (``iter_jax_batches(stack=K)``
    yields [k, B, ...] leaves, k == K except a ragged tail) — the
    StepDriver keys its fused-vs-single dispatch off it.
    """

    def __init__(self, inner: Iterator[Dict[str, Any]], stack: int = 1):
        self._inner = inner
        self.stack = stack
        self.ingest_s = 0.0
        self.compute_s = 0.0
        # the first pull pays pipeline spin-up (dataset execution, actor
        # round trips, prefetch warmup) — booked separately so the verdict
        # describes the steady state, like bench excludes compile/warmup
        self.cold_start_s = 0.0
        self.batches = 0
        self._t_resume: Optional[float] = None

    def __iter__(self) -> "JaxBatchIterator":
        return self

    def __next__(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        if self._t_resume is not None:
            self.compute_s += t0 - self._t_resume
        try:
            batch = next(self._inner)
        except StopIteration:
            self._t_resume = None
            raise
        if self.batches == 0:
            self.cold_start_s += time.perf_counter() - t0
        else:
            self.ingest_s += time.perf_counter() - t0
        self._t_resume = time.perf_counter()
        self.batches += 1
        return batch

    def report(self) -> Dict[str, Any]:
        total = self.ingest_s + self.compute_s
        verdict = ("ingest-limited" if self.ingest_s > self.compute_s
                   else "compute-limited")
        return {
            "verdict": verdict,
            "ingest_s": round(self.ingest_s, 4),
            "compute_s": round(self.compute_s, 4),
            "cold_start_s": round(self.cold_start_s, 4),
            "ingest_frac": round(self.ingest_s / total, 4) if total else 0.0,
            "batches": self.batches,
            "batches_per_s": (round(self.batches / total, 2)
                              if total else 0.0),
        }

    def verdict(self) -> str:
        r = self.report()
        return (f"{r['verdict']}: ingest {r['ingest_s']:.3f}s vs compute "
                f"{r['compute_s']:.3f}s over {r['batches']} batch(es) "
                f"(ingest fraction {r['ingest_frac']:.0%})")


class DataIterator:
    """One consumer's view of a stream of blocks."""

    def __init__(self, block_iter_fn):
        self._block_iter_fn = block_iter_fn

    def _blocks(self) -> Iterator[B.Block]:
        for ref in self._block_iter_fn():
            yield ray_tpu.get(ref) if hasattr(ref, "hex") else ref

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy", drop_last: bool = False,
                     prefetch_batches: int = 1,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None) -> Iterator[Any]:
        it = batches_from_blocks(
            self._blocks(), batch_size, batch_format, drop_last,
            local_shuffle_buffer_size, local_shuffle_seed)
        return prefetched(it, prefetch_batches)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for blk in self._blocks():
            yield from B.iter_rows(blk)

    def iter_torch_batches(self, *, batch_size: int = 256,
                           drop_last: bool = False, dtypes=None,
                           device: Optional[str] = None,
                           prefetch_batches: int = 2
                           ) -> Iterator[Dict[str, Any]]:
        """Batches as torch tensors (reference: ``iter_torch_batches``) —
        the feed path for TorchTrainer loops."""
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       drop_last=drop_last,
                                       prefetch_batches=prefetch_batches):
            out = {}
            for k, v in batch.items():
                t = torch.as_tensor(v)
                dt = (dtypes.get(k) if isinstance(dtypes, dict) else dtypes) \
                    if dtypes is not None else None
                if dt is not None or device is not None:
                    t = t.to(device=device, dtype=dt)  # one cast+transfer
                out[k] = t
            yield out

    def iter_jax_batches(self, *, batch_size: int = 256,
                         drop_last: bool = True, dtype=None,
                         prefetch_batches: int = 2,
                         stack: int = 1) -> "JaxBatchIterator":
        """Batches as jnp device arrays — the TPU feed path (host numpy →
        device put; drop_last defaults True to keep shapes static for jit).

        ``stack=K`` groups K consecutive batches into one [K, B, ...] tree
        (host-side ``np.stack``, then one device put) — the fused-K launch
        feed. A ragged tail yields [k < K, B, ...]; the StepDriver
        single-steps it. The device conversion itself runs ``prefetch_batches``
        ahead on a bounded lookahead thread, so at steady state the
        consumer's ``next()`` returns an already-materialized device batch
        and ``report()`` can honestly say compute-limited. Caveat: the put
        lands on the default device — on a MULTI-device mesh the driver's
        plan placement re-shards each group (one extra device copy); feed
        the driver host batches there and let it stack+place instead.

        Returns a ``JaxBatchIterator``: iterate as before, and call
        ``.report()`` / ``.verdict()`` afterwards for the
        ingest-vs-compute breakdown ("is the pipeline keeping the chips
        fed?")."""
        import numpy as np

        import jax.numpy as jnp

        def host_gen():
            pend = []
            for batch in self.iter_batches(batch_size=batch_size,
                                           drop_last=drop_last,
                                           prefetch_batches=prefetch_batches):
                batch = {k: (np.asarray(v) if dtype is None
                             else np.asarray(v).astype(dtype))
                         for k, v in batch.items()}
                if stack <= 1:
                    yield batch
                    continue
                if pend and any(
                        np.shape(batch[k]) != np.shape(pend[0][k])
                        for k in pend[0]):
                    # a ragged-B batch (drop_last=False) can't stack with
                    # full ones — flush the group, let it ride alone
                    yield {k: np.stack([b[k] for b in pend])
                           for k in pend[0]}
                    pend = []
                pend.append(batch)
                if len(pend) == stack:
                    yield {k: np.stack([b[k] for b in pend])
                           for k in pend[0]}
                    pend = []
            if pend:  # ragged tail: [k < K, B, ...]
                yield {k: np.stack([b[k] for b in pend]) for k in pend[0]}

        def device_gen():
            for batch in host_gen():
                yield {k: jnp.asarray(v) for k, v in batch.items()}

        return JaxBatchIterator(prefetched(device_gen(), prefetch_batches),
                                stack=stack)


@ray_tpu.remote
class _SplitCoordinator:
    """Hands out block *refs* of one executing dataset to N consumers.

    Reference: ``StreamSplitDataIterator`` — blocks are assigned first-come
    (each consumed exactly once); ``equal=True`` balances by row count.
    Only refs flow through this actor — the payloads resolve directly from
    the object plane at each consumer (no coordinator copy bottleneck).

    There is exactly ONE coordinator per ``streaming_split`` call, shared by
    all N iterators, so every consumer sees a split of the *same* dataset
    execution (a private per-rank execution would silently duplicate/drop
    rows under unseeded shuffles). Multi-epoch: when every split has drained
    its queue and requests the next epoch, the dataset is re-executed —
    a barrier across splits, matching the reference's per-epoch re-execution.
    """

    def __init__(self, n: int, equal: bool):
        self._n = n
        self._equal = equal
        self._lock = threading.Lock()
        self._payload = None
        self._filled_epoch = -1
        self._requested = [0] * n
        self._queues: List[collections.deque] = [collections.deque()
                                                 for _ in range(n)]

    def start(self, dataset_payload) -> None:
        """Registers the dataset to execute (first caller wins)."""
        with self._lock:
            if self._payload is None:
                self._payload = dataset_payload

    def _fill(self) -> None:
        # caller holds self._lock
        refs = list(self._payload._execute_refs())
        if self._equal:
            from ray_tpu.data.dataset import _num_rows_task

            rows = ray_tpu.get(
                [_num_rows_task.remote(r) for r in refs])
            order = np.argsort(rows)[::-1]
            loads = [0] * self._n
            for i in order:
                j = int(np.argmin(loads))
                self._queues[j].append(refs[i])
                loads[j] += rows[i]
        else:
            for i, r in enumerate(refs):
                self._queues[i % self._n].append(r)

    def next_block_ref(self, split_idx: int, epoch: int):
        """Returns ("block", ref) | ("end", None) | ("wait", None)."""
        with self._lock:
            if epoch > self._requested[split_idx]:
                # requesting epoch e declares all earlier epochs finished for
                # this split — drop any abandoned remainder (consumer broke
                # out of the iterator mid-epoch) so the barrier can't
                # deadlock on undrained refs
                self._requested[split_idx] = epoch
                self._queues[split_idx].clear()
            if epoch > self._filled_epoch:
                # Next epoch starts only once EVERY split asked for it
                # (each having thereby abandoned/finished the previous one).
                if min(self._requested) >= epoch:
                    for q in self._queues:
                        q.clear()
                    self._fill()
                    self._filled_epoch = epoch
                else:
                    return ("wait", None)
            q = self._queues[split_idx]
            if q:
                return ("block", q.popleft())
            return ("end", None)


class StreamSplitIterator(DataIterator):
    """One consumer's split. Re-iterating starts the next epoch (the dataset
    re-executes once all sibling splits also finish the current epoch)."""

    def __init__(self, coordinator, split_idx: int, dataset):
        self._coord = coordinator
        self._idx = split_idx
        self._ds = dataset
        self._started = False
        self._epoch = 0
        super().__init__(self._pull_blocks)

    def _pull_blocks(self):
        import time

        if not self._started:
            # ship the dataset (plan closures) once, not per block
            ray_tpu.get(self._coord.start.remote(self._ds))
            self._started = True
        epoch = self._epoch
        self._epoch += 1
        delay = 0.02
        while True:
            status, ref = ray_tpu.get(
                self._coord.next_block_ref.remote(self._idx, epoch))
            if status == "wait":
                # barrier wait with backoff: a straggler sibling can lag a
                # whole epoch — don't hammer the coordinator at 20Hz
                time.sleep(delay)
                delay = min(delay * 1.6, 1.0)
                continue
            delay = 0.02
            if status == "end":
                return
            yield ray_tpu.get(ref)
