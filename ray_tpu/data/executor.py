"""Streaming execution of a Dataset's logical plan.

Reference analog: ``data/_internal/execution/streaming_executor.py:49`` +
physical operators (``TaskPoolMapOperator``, ``ActorPoolMapOperator``,
``OutputSplitter``) and the MapFusion rule in ``logical/optimizers.py``.

The planner fuses runs of map-like logical ops into a single remote task per
block (one serialization + one scheduling hop per block, not per op).
Execution is pull-based and streaming: a bounded number of block-tasks are
in flight per stage (backpressure), and downstream consumption drives
upstream submission. All-to-all ops (shuffle/sort/aggregate/repartition)
are barriers: they drain their upstream, run a distributed map/reduce over
tasks, and stream their outputs.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.data import block as B
from ray_tpu.data import logical as L


# ---------------------------------------------------------------------------
# Block transforms compiled from logical ops
# ---------------------------------------------------------------------------


def _compile_map_like(op: L.LogicalOp) -> Callable[[B.Block], B.Block]:
    if isinstance(op, L.MapBatches):
        fn = op.fn
        if isinstance(fn, type):  # class UDF instantiated per-worker elsewhere
            raise TypeError("class UDFs must run on an actor pool")

        def apply_mb(block: B.Block, _i: int) -> B.Block:
            n = B.num_rows(block)
            if n == 0:
                return block
            bs = op.batch_size or n
            outs = []
            for start in range(0, n, bs):
                batch = B.to_batch(B.slice_block(block, start, start + bs),
                                   op.batch_format)
                out = fn(batch, *op.fn_args, **op.fn_kwargs)
                outs.append(B.from_batch(out))
            return B.concat(outs)

        return apply_mb
    if isinstance(op, L.MapRows):
        def apply_rows(block: B.Block, _i: int) -> B.Block:
            return B.from_rows([op.fn(r) for r in B.iter_rows(block)])

        return apply_rows
    if isinstance(op, L.Filter):
        def apply_filter(block: B.Block, _i: int) -> B.Block:
            keep = np.asarray([bool(op.fn(r)) for r in B.iter_rows(block)])
            if not keep.any():
                return {}
            return B.take_rows(block, np.nonzero(keep)[0])

        return apply_filter
    if isinstance(op, L.FlatMap):
        def apply_flat(block: B.Block, _i: int) -> B.Block:
            rows: List[Dict] = []
            for r in B.iter_rows(block):
                rows.extend(op.fn(r))
            return B.from_rows(rows)

        return apply_flat
    if isinstance(op, L.AddColumn):
        def apply_add(block: B.Block, _i: int) -> B.Block:
            if B.num_rows(block) == 0:
                return block
            out = dict(block)
            out[op.name] = np.asarray(op.fn(dict(block)))
            return out

        return apply_add
    if isinstance(op, L.DropColumns):
        return lambda block, _i: {k: v for k, v in block.items()
                                  if k not in op.columns}
    if isinstance(op, L.SelectColumns):
        return lambda block, _i: (
            {} if B.num_rows(block) == 0
            else {k: block[k] for k in op.columns})
    if isinstance(op, L.RandomSample):
        def apply_sample(block: B.Block, block_idx: int) -> B.Block:
            n = B.num_rows(block)
            if n == 0:
                return block
            # per-block salt: a shared seed must not correlate blocks
            seed = None if op.seed is None else op.seed + block_idx
            rng = np.random.default_rng(seed)
            keep = rng.random(n) < op.fraction
            return B.take_rows(block, np.nonzero(keep)[0])

        return apply_sample
    raise TypeError(f"not a map-like op: {op}")


def _run_fused(fns: List[Callable], block: B.Block,
               block_idx: int) -> B.Block:
    for fn in fns:
        block = fn(block, block_idx)
    return block


@ray_tpu.remote(num_returns=2)
def _map_task(fns: List[Callable], block: B.Block, block_idx: int):
    """Returns (block, metadata): the small metadata ref resolves with the
    task and feeds the stage's memory accounting without pulling the block
    (reference: RefBundle carries BlockMetadata)."""
    out = _run_fused(fns, block, block_idx)
    return out, {"nbytes": B.size_bytes(out), "rows": B.num_rows(out)}


@ray_tpu.remote
class _MapActor:
    """Hosts one instance of a callable-class UDF (ActorPoolMapOperator)."""

    def __init__(self, cls_payload, ctor_args, pre_fns, post_fns,
                 batch_size, batch_format, fn_args, fn_kwargs):
        self._udf = cls_payload(*ctor_args)
        self._pre = pre_fns
        self._post = post_fns
        self._bs = batch_size
        self._fmt = batch_format
        self._args = fn_args
        self._kwargs = fn_kwargs

    def map(self, block: B.Block, block_idx: int) -> B.Block:
        block = _run_fused(self._pre, block, block_idx)
        n = B.num_rows(block)
        if n:
            bs = self._bs or n
            outs = []
            for start in range(0, n, bs):
                batch = B.to_batch(B.slice_block(block, start, start + bs),
                                   self._fmt)
                outs.append(B.from_batch(
                    self._udf(batch, *self._args, **self._kwargs)))
            block = B.concat(outs)
        return _run_fused(self._post, block, block_idx)


# ---------------------------------------------------------------------------
# Execution statistics (reference: ``data/_internal/stats.py`` DatasetStats —
# the per-operator accounting behind ``Dataset.stats()``)
# ---------------------------------------------------------------------------


class ExecutionStats:
    """Per-operator accounting of one streaming execution.

    Each operator entry holds gross wall time (time spent inside that
    stage's iterator, which INCLUDES its upstream — streaming pulls nest),
    block/row/byte counts, and any stage-specific counters (submitted
    tasks, backpressure events). ``summary()`` nets out the nesting so the
    per-operator walls are additive."""

    def __init__(self):
        self.entries: List[Dict[str, Any]] = []
        self.started_at = time.time()

    def new_entry(self, operator: str,
                  stage: Optional["Stage"] = None) -> Dict[str, Any]:
        entry = {"operator": operator, "wall_s": 0.0, "blocks": 0,
                 "stage": stage}
        self.entries.append(entry)
        return entry

    def summary(self) -> List[Dict[str, Any]]:
        # Close the books lazily: stages with deferred accounting (map
        # stages waiting on straggler metadata refs) settle only when
        # stats are actually read — never on the streaming hot path.
        for e in self.entries:
            stage = e.get("stage")
            if hasattr(stage, "finalize_stats"):
                stage.finalize_stats()
        out: List[Dict[str, Any]] = []
        prev_gross = 0.0
        for e in self.entries:
            row = {"operator": e["operator"], "blocks": e["blocks"],
                   "wall_s": max(0.0, e["wall_s"] - prev_gross),
                   "gross_s": e["wall_s"]}
            prev_gross = e["wall_s"]
            stage = e.get("stage")
            stats = getattr(stage, "stats", None)
            if stats:
                for k in ("submitted", "rows", "bytes",
                          "backpressure_events"):
                    if k in stats:
                        row[k] = stats[k]
            out.append(row)
        return out

    def to_string(self) -> str:
        rows = self.summary()
        if not rows:
            return "(no execution recorded)"
        total = rows[-1]["gross_s"] if rows else 0.0
        lines = [f"Execution: {len(rows)} operator(s), "
                 f"{total:.3f}s total wall"]
        for i, r in enumerate(rows):
            parts = [f"{r['blocks']} block(s)", f"{r['wall_s']:.3f}s wall"]
            if r.get("rows"):
                parts.append(f"{r['rows']} rows")
            if r.get("bytes"):
                parts.append(f"{r['bytes'] / 1e6:.2f} MB")
            if r.get("submitted") is not None:
                parts.append(f"{r['submitted']} task(s)")
            if r.get("backpressure_events"):
                parts.append(
                    f"{r['backpressure_events']} backpressure event(s)")
            lines.append(f"Operator {i} {r['operator']}: "
                         + ", ".join(parts))
        return "\n".join(lines)


def _instrumented(it: Iterator, entry: Dict[str, Any]) -> Iterator:
    """Wrap a stage's output iterator with wall/block accounting."""
    while True:
        t0 = time.perf_counter()
        try:
            item = next(it)
        except StopIteration:
            entry["wall_s"] += time.perf_counter() - t0
            return
        entry["wall_s"] += time.perf_counter() - t0
        entry["blocks"] += 1
        yield item


# ---------------------------------------------------------------------------
# Stages
# ---------------------------------------------------------------------------


class Stage:
    label = "Stage"

    def run(self, upstream: Iterator, ctx) -> Iterator:
        raise NotImplementedError


class MapStage(Stage):
    """Bounded-in-flight map over blocks with resource-aware backpressure.

    Reference analog: ``TaskPoolMapOperator`` under
    ``streaming_executor_state.py:55`` (``TopologyResourceUsage``): the
    stage stops submitting when (a) the task-count cap is reached, (b) the
    count exceeds the cluster's CPU slots x oversubscription, or (c) the
    estimated bytes of in-flight outputs (EWMA of completed block sizes)
    exceed the stage's memory budget — so a fast producer ahead of a slow
    consumer is throttled instead of buffering the whole dataset.
    """

    def __init__(self, fns: List[Callable], options: Dict[str, Any],
                 label: str = "Map"):
        self.fns = fns
        self.options = options
        self.label = label
        self.stats: Dict[str, Any] = {"submitted": 0, "completed_meta": 0,
                                      "bytes_ewma": 0.0, "rows": 0,
                                      "bytes": 0, "backpressure_events": 0}
        self._pending_meta: List = []

    def _harvest_meta(self, block: bool = False) -> None:
        """Fold completed metadata refs into the stats/EWMA. ``block``
        waits (bounded) for stragglers — used only by ``finalize_stats``,
        never on the streaming path."""
        if not self._pending_meta:
            return
        try:
            if block:  # failed tasks resolve metas with the error payload
                ray_tpu.wait(self._pending_meta,
                             num_returns=len(self._pending_meta),
                             timeout=30)
            done, rest = ray_tpu.wait(self._pending_meta,
                                      num_returns=len(self._pending_meta),
                                      timeout=0)
        except Exception:  # noqa: BLE001 — e.g. stats() read after
            return  # shutdown: report stays partial, never raises
        self._pending_meta[:] = rest
        for m in done:
            try:
                meta = ray_tpu.get(m)
            except Exception:  # noqa: BLE001 — error surfaces via block
                continue
            prev = self.stats["bytes_ewma"]
            self.stats["bytes_ewma"] = (
                meta["nbytes"] if not prev
                else 0.7 * prev + 0.3 * meta["nbytes"])
            self.stats["completed_meta"] += 1
            self.stats["rows"] += meta["rows"]
            self.stats["bytes"] += meta["nbytes"]

    def finalize_stats(self) -> None:
        """Settle straggler metadata so stats() reports full row/byte
        totals; called from ExecutionStats.summary() at read time."""
        self._harvest_meta(block=True)

    def _count_cap(self, ctx) -> int:
        cap = ctx.max_tasks_in_flight
        if getattr(ctx, "cpu_oversubscription", 0):
            try:
                cpus = ray_tpu.cluster_resources().get("CPU", 0)
            except Exception:  # noqa: BLE001 — sizing hint only
                cpus = 0
            if cpus:
                task_cpus = self.options.get("num_cpus") or 1
                cap = min(cap, max(1, int(
                    cpus / task_cpus * ctx.cpu_oversubscription)))
        return cap

    def run(self, upstream: Iterator, ctx) -> Iterator:
        max_inflight = self._count_cap(ctx)
        mem_budget = getattr(ctx, "memory_budget_bytes", 0)
        task = _map_task.options(**self.options) if self.options else _map_task
        inflight: collections.deque = collections.deque()
        upstream = iter(upstream)
        exhausted = False
        block_idx = 0

        def over_memory() -> bool:
            if not mem_budget or not self.stats["bytes_ewma"]:
                return False
            est = len(inflight) * self.stats["bytes_ewma"]
            if est >= mem_budget:
                self.stats["backpressure_events"] += 1
                return True
            return False

        while True:
            self._harvest_meta()
            while (not exhausted and len(inflight) < max_inflight
                   and not over_memory()):
                try:
                    ref = next(upstream)
                except StopIteration:
                    exhausted = True
                    break
                block_ref, meta_ref = task.remote(self.fns, ref, block_idx)
                inflight.append(block_ref)
                self._pending_meta.append(meta_ref)
                self.stats["submitted"] += 1
                block_idx += 1
            if not inflight:
                return
            yield inflight.popleft()


class ActorMapStage(Stage):
    def __init__(self, op: L.MapBatches, pre: List[Callable],
                 post: List[Callable]):
        self.op = op
        self.pre = pre
        self.post = post
        self.label = f"ActorMap({getattr(op.fn, '__name__', 'udf')})"

    def run(self, upstream: Iterator, ctx) -> Iterator:
        """Autoscaling pool (reference: ``ActorPoolMapOperator`` +
        ``AutoscalingPolicy``): start at min_size and add actors while the
        upstream still has blocks and every slot is busy (up to max_size).
        The whole pool is released in the ``finally`` once every issued call
        has materialized."""
        op = self.op
        strategy = op.compute or L.ActorPoolStrategy(size=2)
        lo = strategy.size or strategy.min_size
        hi = strategy.size or max(strategy.max_size or lo, lo)
        opts: Dict[str, Any] = {}
        if op.num_cpus is not None:
            opts["num_cpus"] = op.num_cpus
        if op.num_tpus:
            opts["num_tpus"] = op.num_tpus
        actor_cls = _MapActor.options(**opts) if opts else _MapActor
        pool: List[Any] = []
        counts: Dict[int, int] = {}

        def add_actor() -> None:
            counts[len(pool)] = 0
            pool.append(actor_cls.remote(
                op.fn, op.fn_constructor_args, self.pre, self.post,
                op.batch_size, op.batch_format, op.fn_args, op.fn_kwargs))

        for _ in range(lo):
            add_actor()
        per_actor_cap = 2
        inflight: collections.deque = collections.deque()
        issued: List = []
        upstream = iter(upstream)
        exhausted = False
        block_idx = 0
        try:
            while True:
                while (not exhausted
                       and len(inflight) < len(pool) * per_actor_cap):
                    try:
                        ref = next(upstream)
                    except StopIteration:
                        exhausted = True
                        break
                    i = min(counts, key=counts.get)
                    counts[i] += 1
                    out = pool[i].map.remote(ref, block_idx)
                    block_idx += 1
                    issued.append(out)
                    inflight.append((i, out))
                if (not exhausted and len(pool) < hi
                        and all(c >= per_actor_cap for c in counts.values())):
                    add_actor()  # demand outruns capacity: scale up
                    continue
                if not inflight:
                    return
                i, out = inflight.popleft()
                counts[i] -= 1
                yield out
        finally:
            # downstream may hold yielded refs unresolved (e.g. an
            # all-to-all barrier collects refs first) — don't kill the
            # pool until every issued call has materialized its result
            if issued:
                try:
                    ray_tpu.wait(issued, num_returns=len(issued),
                                 timeout=300)
                except Exception:
                    pass
            for a in pool:
                try:
                    ray_tpu.kill(a, no_restart=True)
                except Exception:
                    pass


class LimitStage(Stage):
    def __init__(self, n: int):
        self.n = n
        self.label = f"Limit({n})"

    def run(self, upstream: Iterator, ctx) -> Iterator:
        remaining = self.n
        for ref in upstream:
            if remaining <= 0:
                return
            blk = ray_tpu.get(ref)
            rows = B.num_rows(blk)
            if rows <= remaining:
                remaining -= rows
                yield ref
            else:
                yield ray_tpu.put(B.slice_block(blk, 0, remaining))
                remaining = 0
            if remaining == 0:
                return


@ray_tpu.remote
def _split_task(block: B.Block, n_out: int, seed: Optional[int],
                salt: int, mode: str, boundaries=None, key=None):
    """Shuffle/sort/groupby map phase: partition one block n_out ways."""
    n = B.num_rows(block)
    if n == 0:  # Filter/RandomSample legitimately produce empty blocks
        parts = [{} for _ in range(n_out)]
        return parts if n_out > 1 else parts[0]
    if mode == "shuffle":
        rng = np.random.default_rng(None if seed is None else seed + salt)
        perm = rng.permutation(n)
        assignment = perm % n_out
    elif mode == "range":  # sort: range-partition by key against boundaries
        vals = block[key]
        assignment = np.searchsorted(boundaries, vals, side="right")
    elif mode == "hash":  # groupby: hash-partition by key
        import zlib

        vals = block[key]
        if vals.dtype.kind in "USO":
            # NOT hash(): process-salted, differs across worker processes
            assignment = np.asarray(
                [zlib.crc32(str(x).encode()) % n_out for x in vals])
        else:
            assignment = vals.astype(np.int64) % n_out
    else:
        raise ValueError(mode)
    parts = [B.take_rows(block, np.nonzero(assignment == i)[0])
             for i in range(n_out)]
    return parts if n_out > 1 else parts[0]


def _merge_sort(parts: List[B.Block], key: str, descending: bool) -> B.Block:
    merged = B.concat(list(parts))
    if B.num_rows(merged) == 0:
        return merged
    order = np.argsort(merged[key], kind="stable")
    if descending:
        order = order[::-1]
    return B.take_rows(merged, order)


def _merge_aggregate(parts: List[B.Block], key, aggs) -> B.Block:
    from ray_tpu.data.aggregate import aggregate_block

    return aggregate_block(B.concat(list(parts)), key, aggs)


@ray_tpu.remote
def _reduce_concat(*parts):
    return B.concat(list(parts))


@ray_tpu.remote
def _reduce_sort(key: str, descending: bool, *parts):
    return _merge_sort(list(parts), key, descending)


@ray_tpu.remote
def _reduce_aggregate(key, aggs, *parts):
    return _merge_aggregate(list(parts), key, aggs)


def _all_to_all(refs: List, n_out: int, mode: str, reduce_task,
                reduce_args: Tuple = (), seed=None, boundaries=None,
                key=None) -> List:
    """Two-phase map/reduce over tasks, or — when
    ``DataContext.use_push_based_shuffle`` — a pipelined merge through
    per-partition merger actors (reference:
    ``_internal/push_based_shuffle.py``, the Exoshuffle design)."""
    if not refs:
        return []
    from ray_tpu.data.context import DataContext

    if DataContext.get_current().use_push_based_shuffle:
        return _push_based_all_to_all(refs, n_out, mode, reduce_args,
                                      seed=seed, boundaries=boundaries,
                                      key=key)
    part_lists = [
        _split_task.options(num_returns=n_out).remote(
            ref, n_out, seed, i, mode, boundaries, key)
        for i, ref in enumerate(refs)
    ]
    if n_out == 1:
        part_lists = [[p] for p in part_lists]
    return [
        reduce_task.remote(*reduce_args, *[parts[j] for parts in part_lists])
        for j in range(n_out)
    ]


@ray_tpu.remote
class _ShuffleMerger:
    """One output partition's incremental merger: map outputs stream in via
    ``add`` (pipelined with still-running map tasks) and are merged every
    few parts, so partition memory stays bounded; ``finalize`` applies the
    mode's reduction (concat / sort / aggregate)."""

    _MERGE_EVERY = 8

    def __init__(self, mode: str, reduce_args: Tuple = ()):
        self._mode = mode
        self._args = reduce_args
        self._parts: List[B.Block] = []

    def _compact(self) -> None:
        # concat-only: aggregates are NOT associative as row-blocks (a
        # Count of counts is wrong), so aggregation happens once in
        # finalize; sort likewise sorts once over the full partition
        self._parts = [B.concat(self._parts)]

    def add(self, part: B.Block) -> bool:
        self._parts.append(part)
        if len(self._parts) >= self._MERGE_EVERY:
            self._compact()
        return True

    def finalize(self) -> B.Block:
        if not self._parts:
            return {}
        if self._mode == "sort":
            return _merge_sort(self._parts, *self._args)
        if self._mode == "aggregate":
            return _merge_aggregate(self._parts, *self._args)
        return B.concat(self._parts)


def _push_based_all_to_all(refs: List, n_out: int, mode: str,
                           reduce_args: Tuple, seed=None, boundaries=None,
                           key=None) -> List:
    reduce_mode = {"shuffle": "concat", "range": "sort",
                   "hash": "aggregate"}[mode]
    mergers = [_ShuffleMerger.remote(reduce_mode, reduce_args)
               for _ in range(n_out)]
    acks = []
    for i, ref in enumerate(refs):
        parts = _split_task.options(num_returns=n_out).remote(
            ref, n_out, seed, i, mode, boundaries, key)
        if n_out == 1:
            parts = [parts]
        acks.extend(mergers[j].add.remote(parts[j]) for j in range(n_out))
    # Ordering: an actor's finalize is per-caller-FIFO behind its adds, so
    # finalize refs could be returned immediately — but the acks must be
    # GOT (not just waited): a failed map task errors its add calls, and
    # only get() raises, preventing a silently truncated shuffle.
    if acks:
        ray_tpu.get(acks)  # unbounded, like the task-graph path
    out = [m.finalize.remote() for m in mergers]
    # release merger actors once every finalize has materialized
    import threading

    def _reap(ms=list(mergers), fs=list(out)):
        try:  # unbounded: killing a merger mid-finalize loses its partition
            ray_tpu.wait(fs, num_returns=len(fs), timeout=None)
        except Exception:  # noqa: BLE001
            pass
        for m in ms:
            try:
                ray_tpu.kill(m, no_restart=True)
            except Exception:  # noqa: BLE001
                pass

    threading.Thread(target=_reap, daemon=True).start()
    return out


class AllToAllStage(Stage):
    def __init__(self, op: L.LogicalOp):
        self.op = op
        self.label = type(op).__name__

    def run(self, upstream: Iterator, ctx) -> Iterator:
        refs = list(upstream)
        op = self.op
        if isinstance(op, L.RandomShuffle):
            n_out = max(1, len(refs))
            out = _all_to_all(refs, n_out, "shuffle", _reduce_concat,
                              seed=op.seed)
            # shuffle output block order too for better randomness
            rng = np.random.default_rng(op.seed)
            out = [out[i] for i in rng.permutation(len(out))]
        elif isinstance(op, L.Repartition):
            n_out = op.num_blocks
            out = _all_to_all(refs, n_out, "shuffle", _reduce_concat, seed=0)
        elif isinstance(op, L.Sort):
            n_out = max(1, len(refs))
            boundaries = self._sample_boundaries(refs, op.key, n_out)
            out = _all_to_all(refs, n_out, "range",
                              _reduce_sort, (op.key, op.descending),
                              boundaries=boundaries, key=op.key)
            if op.descending:
                out = out[::-1]
        elif isinstance(op, L.Aggregate):
            if op.key is None:
                out = [_reduce_aggregate.remote(None, op.aggs, *refs)]
            else:
                n_out = min(max(1, len(refs)), 8)
                out = _all_to_all(refs, n_out, "hash",
                                  _reduce_aggregate, (op.key, op.aggs),
                                  key=op.key)
        else:
            raise TypeError(f"unknown all-to-all op {op}")
        yield from out

    @staticmethod
    def _sample_boundaries(refs: List, key: str, n_out: int) -> np.ndarray:
        samples = []
        for ref in refs[:20]:
            blk = ray_tpu.get(ref)
            if B.num_rows(blk):
                vals = blk[key]
                k = min(len(vals), 32)
                samples.append(np.random.default_rng(0).choice(
                    vals, size=k, replace=False))
        if not samples:
            return np.asarray([0.0] * (n_out - 1))
        allv = np.sort(np.concatenate(samples))
        qs = [allv[int(len(allv) * i / n_out)] for i in range(1, n_out)]
        return np.asarray(qs)


class UnionStage(Stage):
    label = "Union"

    def __init__(self, other_iterables: List):
        self.others = other_iterables

    def run(self, upstream: Iterator, ctx) -> Iterator:
        yield from upstream
        for it in self.others:
            yield from it


class ZipStage(Stage):
    label = "Zip"

    def __init__(self, other_iterable):
        self.other = other_iterable

    def run(self, upstream: Iterator, ctx) -> Iterator:
        left = B.concat([ray_tpu.get(r) for r in upstream])
        right = B.concat([ray_tpu.get(r) for r in self.other])
        if B.num_rows(left) != B.num_rows(right):
            raise ValueError(
                f"zip requires equal row counts "
                f"({B.num_rows(left)} vs {B.num_rows(right)})")
        merged = dict(left)
        for k, v in right.items():
            merged[k + "_1" if k in merged else k] = v
        yield ray_tpu.put(merged)


# ---------------------------------------------------------------------------
# Planner
# ---------------------------------------------------------------------------


def plan(ops: List[L.LogicalOp]) -> List[Stage]:
    stages: List[Stage] = []
    pending_fns: List[Callable] = []
    pending_opts: Dict[str, Any] = {}
    pending_names: List[str] = []

    def flush():
        nonlocal pending_fns, pending_opts, pending_names
        if pending_fns:
            stages.append(MapStage(pending_fns, pending_opts,
                                   label="Map[" + "+".join(pending_names)
                                         + "]"))
            pending_fns, pending_opts, pending_names = [], {}, []

    for op in ops:
        if isinstance(op, L.MapBatches) and (
                isinstance(op.fn, type) or op.compute is not None):
            # stateful UDF: fuse preceding maps into the actor, flush after
            pre = pending_fns
            pending_fns, pending_opts, pending_names = [], {}, []
            stages.append(ActorMapStage(op, pre, []))
        elif isinstance(op, L.MAP_LIKE):
            opts = {}
            if isinstance(op, L.MapBatches):
                if op.num_cpus is not None:
                    opts["num_cpus"] = op.num_cpus
                if op.num_tpus:
                    opts["num_tpus"] = op.num_tpus
            if opts != pending_opts:
                # fuse only ops with identical resource requests — a
                # resource change (including back to default) splits stages
                flush()
                pending_opts = opts
            pending_fns.append(_compile_map_like(op))
            pending_names.append(type(op).__name__)
        elif isinstance(op, L.Limit):
            flush()
            stages.append(LimitStage(op.n))
        elif isinstance(op, (L.RandomShuffle, L.Repartition, L.Sort,
                             L.Aggregate)):
            flush()
            stages.append(AllToAllStage(op))
        elif isinstance(op, L.Union):
            flush()
            stages.append(UnionStage(
                [o._execute_refs() for o in op.others]))
        elif isinstance(op, L.Zip):
            flush()
            stages.append(ZipStage(op.other._execute_refs()))
        else:
            raise TypeError(f"unknown logical op {op}")
    flush()
    return stages


def execute_streaming(source: Iterator, ops: List[L.LogicalOp],
                      ctx, stats: Optional[ExecutionStats] = None
                      ) -> Iterator:
    """Returns an iterator of block ObjectRefs. ``stats`` (an
    ExecutionStats) receives per-operator wall/block accounting — the
    backing store of ``Dataset.stats()``."""
    it = source
    if stats is not None:
        it = _instrumented(iter(it), stats.new_entry("Read"))
    for stage in plan(ops):
        it = stage.run(it, ctx)
        if stats is not None:
            it = _instrumented(iter(it),
                               stats.new_entry(stage.label, stage))
    return it
