"""Logical operators: the lazy plan a Dataset accumulates.

Reference analog: ``data/_internal/logical/operators/`` (``Read``,
``MapBatches/MapRows/Filter/FlatMap`` ``map_operator.py:103-190``,
``RandomShuffle/Repartition/Sort/Aggregate`` ``all_to_all_operator.py``,
``Zip/Union/Limit/Write``). The planner (executor.py) fuses consecutive
map-like ops into single tasks — the reference's MapFusion rule
(``logical/optimizers.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclasses.dataclass
class LogicalOp:
    pass


@dataclasses.dataclass
class MapBatches(LogicalOp):
    fn: Any  # callable or callable-class
    batch_size: Optional[int]
    batch_format: str = "numpy"
    fn_args: Tuple = ()
    fn_kwargs: Dict = dataclasses.field(default_factory=dict)
    compute: Optional[Any] = None  # ActorPoolStrategy for class UDFs
    fn_constructor_args: Tuple = ()
    num_tpus: float = 0
    num_cpus: Optional[float] = None


@dataclasses.dataclass
class MapRows(LogicalOp):
    fn: Callable


@dataclasses.dataclass
class Filter(LogicalOp):
    fn: Callable


@dataclasses.dataclass
class FlatMap(LogicalOp):
    fn: Callable


@dataclasses.dataclass
class AddColumn(LogicalOp):
    name: str
    fn: Callable


@dataclasses.dataclass
class DropColumns(LogicalOp):
    columns: List[str]


@dataclasses.dataclass
class SelectColumns(LogicalOp):
    columns: List[str]


@dataclasses.dataclass
class Limit(LogicalOp):
    n: int


@dataclasses.dataclass
class RandomShuffle(LogicalOp):
    seed: Optional[int] = None


@dataclasses.dataclass
class Repartition(LogicalOp):
    num_blocks: int


@dataclasses.dataclass
class Sort(LogicalOp):
    key: str
    descending: bool = False


@dataclasses.dataclass
class Aggregate(LogicalOp):
    key: Optional[str]
    aggs: List[Any]  # AggregateFn list


@dataclasses.dataclass
class Union(LogicalOp):
    others: List[Any]  # Datasets


@dataclasses.dataclass
class Zip(LogicalOp):
    other: Any  # Dataset


@dataclasses.dataclass
class RandomSample(LogicalOp):
    fraction: float
    seed: Optional[int] = None


MAP_LIKE = (MapBatches, MapRows, Filter, FlatMap, AddColumn, DropColumns,
            SelectColumns, RandomSample)


@dataclasses.dataclass
class ActorPoolStrategy:
    """Compute strategy for stateful (callable-class) map_batches UDFs —
    the reference's ``ActorPoolMapOperator`` autoscaling pool."""

    size: Optional[int] = None
    min_size: int = 1
    max_size: Optional[int] = None
