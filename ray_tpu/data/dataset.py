"""Dataset: the lazy, streaming, distributed data API.

Reference analog: ``data/dataset.py:178`` (``Dataset``) + the creation
functions in ``data/read_api.py``. A Dataset is (read tasks, logical ops);
nothing executes until consumption, and consumption streams: blocks flow
through fused map tasks with bounded in-flight parallelism
(executor.execute_streaming). ``materialize()`` pins the result refs.
"""

from __future__ import annotations

import builtins
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.data import aggregate as agg_mod
from ray_tpu.data import block as B
from ray_tpu.data import datasource as ds_mod
from ray_tpu.data import logical as L
from ray_tpu.data.context import DataContext
from ray_tpu.data.iterator import (
    DataIterator,
    StreamSplitIterator,
    _SplitCoordinator,
    batches_from_blocks,
    prefetched,
)


@ray_tpu.remote(num_returns="streaming")
def _read_task_stream(task):
    """Streaming read: a thunk returning a generator yields one ref per
    sub-block (e.g. per parquet row group) so downstream stages start before
    the file is fully read; a plain Block becomes a single item."""
    import types

    out = task()
    if isinstance(out, types.GeneratorType):
        for block in out:
            yield block
    else:
        yield out


@ray_tpu.remote
def _write_task(block: B.Block, path: str, fmt: str, index: int) -> str:
    return ds_mod.write_block(block, path, fmt, index)


@ray_tpu.remote
def _num_rows_task(block: B.Block) -> int:
    return B.num_rows(block)


class Dataset:
    def __init__(self, read_tasks: Optional[List] = None,
                 ops: Optional[List[L.LogicalOp]] = None,
                 materialized_refs: Optional[List] = None):
        self._read_tasks = read_tasks or []
        self._ops = ops or []
        self._materialized = materialized_refs
        # (n, equal) -> shared StreamSplitIterators of ONE execution
        self._stream_splits: Dict = {}

    def _with_op(self, op: L.LogicalOp) -> "Dataset":
        return Dataset(self._read_tasks, self._ops + [op], self._materialized)

    def __getstate__(self):
        # the split cache holds actor handles + a cycle back to this dataset;
        # never ship it with the plan (nor process-local execution stats)
        state = dict(self.__dict__)
        state["_stream_splits"] = {}
        state.pop("_last_stats", None)
        return state

    # ---- execution ----

    def _source_refs(self) -> Iterator:
        if self._materialized is not None:
            yield from self._materialized
            return
        ctx = DataContext.get_current()
        import collections

        inflight: collections.deque = collections.deque()
        tasks = iter(self._read_tasks)
        exhausted = False
        while True:
            while not exhausted and len(inflight) < ctx.max_tasks_in_flight:
                try:
                    t = next(tasks)
                except StopIteration:
                    exhausted = True
                    break
                # one streaming task per read thunk: block refs flow back
                # incrementally (multi-block readers overlap read & compute)
                inflight.append(iter(_read_task_stream.remote(t)))
            if not inflight:
                return
            yield from inflight.popleft()

    def _execute_refs(self) -> Iterator:
        from ray_tpu.data.executor import ExecutionStats, execute_streaming

        ctx = DataContext.get_current()
        ops = self._ops
        src = self
        if ctx.optimizer_enabled and self._materialized is None:
            from ray_tpu.data.optimizer import optimize

            read_tasks, ops, _ = optimize(self._read_tasks, self._ops)
            if read_tasks is not self._read_tasks:
                src = Dataset(read_tasks, [])
        # per-operator accounting of this (the most recent) execution —
        # the backing store of ``stats()``
        self._last_stats = ExecutionStats()
        return execute_streaming(src._source_refs(), ops, ctx,
                                 stats=self._last_stats)

    def explain(self) -> str:
        """Before/after logical plan with the optimizer rules applied
        (reference: ``Dataset.explain``/plan logging)."""
        from ray_tpu.data.optimizer import explain

        return explain(self._read_tasks, self._ops)

    def materialize(self) -> "Dataset":
        refs = list(self._execute_refs())
        return Dataset(materialized_refs=refs)

    # ---- transforms (lazy) ----

    def map_batches(self, fn, *, batch_size: Optional[int] = None,
                    batch_format: str = "numpy", compute=None,
                    fn_args=(), fn_kwargs=None, fn_constructor_args=(),
                    num_cpus: Optional[float] = None,
                    num_tpus: float = 0) -> "Dataset":
        return self._with_op(L.MapBatches(
            fn, batch_size, batch_format, tuple(fn_args), fn_kwargs or {},
            compute, tuple(fn_constructor_args), num_tpus, num_cpus))

    def map(self, fn: Callable) -> "Dataset":
        return self._with_op(L.MapRows(fn))

    def filter(self, fn: Callable) -> "Dataset":
        return self._with_op(L.Filter(fn))

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._with_op(L.FlatMap(fn))

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        return self._with_op(L.AddColumn(name, fn))

    def drop_columns(self, columns: List[str]) -> "Dataset":
        return self._with_op(L.DropColumns(columns))

    def select_columns(self, columns: List[str]) -> "Dataset":
        return self._with_op(L.SelectColumns(columns))

    def limit(self, n: int) -> "Dataset":
        return self._with_op(L.Limit(n))

    def random_sample(self, fraction: float,
                      seed: Optional[int] = None) -> "Dataset":
        return self._with_op(L.RandomSample(fraction, seed))

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        return self._with_op(L.RandomShuffle(seed))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with_op(L.Repartition(num_blocks))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._with_op(L.Sort(key, descending))

    def union(self, *others: "Dataset") -> "Dataset":
        return self._with_op(L.Union(list(others)))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._with_op(L.Zip(other))

    # ---- groupby / aggregates ----

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def aggregate(self, *aggs) -> Dict[str, Any]:
        ds = self._with_op(L.Aggregate(None, list(aggs)))
        out = B.concat([ray_tpu.get(r) for r in ds._execute_refs()])
        return {k: v[0].item() if hasattr(v[0], "item") else v[0]
                for k, v in out.items()}

    def sum(self, on: str):
        return self.aggregate(agg_mod.Sum(on))[f"sum({on})"]

    def min(self, on: str):
        return self.aggregate(agg_mod.Min(on))[f"min({on})"]

    def max(self, on: str):
        return self.aggregate(agg_mod.Max(on))[f"max({on})"]

    def mean(self, on: str):
        return self.aggregate(agg_mod.Mean(on))[f"mean({on})"]

    def std(self, on: str):
        return self.aggregate(agg_mod.Std(on))[f"std({on})"]

    # ---- consumption ----

    def count(self) -> int:
        # row counts resolve remotely — blocks never transfer to the driver
        return sum(ray_tpu.get(
            [_num_rows_task.remote(r) for r in self._execute_refs()]))

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        rows: List[Dict] = []
        for ref in self.limit(n)._execute_refs():
            rows.extend(B.iter_rows(ray_tpu.get(ref)))
            if len(rows) >= n:
                break
        return rows[:n]

    def take_all(self) -> List[Dict[str, Any]]:
        rows: List[Dict] = []
        for ref in self._execute_refs():
            rows.extend(B.iter_rows(ray_tpu.get(ref)))
        return rows

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def schema(self) -> Optional[Dict[str, str]]:
        for ref in self._execute_refs():
            blk = ray_tpu.get(ref)
            if B.num_rows(blk):
                return {k: str(v.dtype) for k, v in blk.items()}
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s) if s else []

    def num_blocks(self) -> int:
        return len(list(self._execute_refs()))

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        return self.iterator().iter_rows()

    def iter_batches(self, **kwargs) -> Iterator[Any]:
        return self.iterator().iter_batches(**kwargs)

    def iter_jax_batches(self, **kwargs) -> Iterator[Dict[str, Any]]:
        return self.iterator().iter_jax_batches(**kwargs)

    def iter_torch_batches(self, **kwargs) -> Iterator[Dict[str, Any]]:
        return self.iterator().iter_torch_batches(**kwargs)

    def iterator(self) -> DataIterator:
        return DataIterator(self._execute_refs)

    def to_pandas(self):
        return B.to_pandas(
            B.concat([ray_tpu.get(r) for r in self._execute_refs()]))

    # ---- splits ----

    def split(self, n: int) -> List["Dataset"]:
        refs = list(self._execute_refs())
        return [Dataset(materialized_refs=refs[i::n])
                for i in builtins.range(n)]

    def streaming_split(self, n: int, equal: bool = False) -> List[DataIterator]:
        """N iterators over ONE shared execution of this dataset.

        Repeated calls with the same (n, equal) return the *same* iterator
        objects backed by one coordinator actor — so per-rank callers (e.g.
        one call per train worker) still split a single execution instead of
        each privately re-executing the pipeline (which would duplicate and
        drop rows under unseeded shuffles)."""
        key = (n, equal)
        cached = self._stream_splits.get(key)
        if cached is None:
            coord = _SplitCoordinator.options(num_cpus=0).remote(n, equal)
            cached = [StreamSplitIterator(coord, i, self)
                      for i in builtins.range(n)]
            self._stream_splits[key] = cached
        return cached

    def reset_streaming_split(self) -> None:
        """Drop cached streaming_split coordinators so the next call starts
        a fresh execution. Callers that restart consumption from scratch
        (e.g. JaxTrainer's failure-recovery retry) must reset — a drained
        coordinator would otherwise hand the restarted consumers an
        immediately-empty stream."""
        self._stream_splits = {}

    def train_test_split(self, test_size: float,
                         shuffle: bool = False,
                         seed: Optional[int] = None):
        ds = self.random_shuffle(seed) if shuffle else self
        rows = ds.take_all()
        cut = int(len(rows) * (1 - test_size))
        return (from_items(rows[:cut]), from_items(rows[cut:]))

    # ---- writes ----

    def _write(self, path: str, fmt: str) -> List[str]:
        return ray_tpu.get([
            _write_task.remote(ref, path, fmt, i)
            for i, ref in enumerate(self._execute_refs())])

    def write_parquet(self, path: str) -> List[str]:
        return self._write(path, "parquet")

    def write_csv(self, path: str) -> List[str]:
        return self._write(path, "csv")

    def write_json(self, path: str) -> List[str]:
        return self._write(path, "json")

    def write_numpy(self, path: str) -> List[str]:
        return self._write(path, "npy")

    def write_tfrecords(self, path: str) -> List[str]:
        return self._write(path, "tfrecords")

    def stats(self) -> str:
        """Per-operator execution report of the MOST RECENT execution of
        this dataset (reference: ``Dataset.stats()`` / DatasetStats): wall
        time, blocks, rows/bytes (map stages), submitted task counts, and
        backpressure events. Consume the dataset first — ``stats()`` never
        triggers an execution itself."""
        stats = getattr(self, "_last_stats", None)
        if stats is None or not stats.entries:
            return (f"Dataset(read_tasks={len(self._read_tasks)}, "
                    f"ops={len(self._ops)}) — not executed yet; consume "
                    f"it (iterate / materialize / count) then call "
                    f".stats()")
        return stats.to_string()

    def __repr__(self) -> str:
        return (f"Dataset(read_tasks={len(self._read_tasks)}, "
                f"ops={[type(o).__name__ for o in self._ops]})")


class GroupedData:
    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def aggregate(self, *aggs) -> Dataset:
        return self._ds._with_op(L.Aggregate(self._key, list(aggs)))

    def count(self) -> Dataset:
        return self.aggregate(agg_mod.Count())

    def sum(self, on: str) -> Dataset:
        return self.aggregate(agg_mod.Sum(on))

    def min(self, on: str) -> Dataset:
        return self.aggregate(agg_mod.Min(on))

    def max(self, on: str) -> Dataset:
        return self.aggregate(agg_mod.Max(on))

    def mean(self, on: str) -> Dataset:
        return self.aggregate(agg_mod.Mean(on))


# ---------------------------------------------------------------------------
# Creation API (reference: data/read_api.py)
# ---------------------------------------------------------------------------


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    p = parallelism if parallelism > 0 else DataContext.get_current().read_parallelism
    return Dataset(ds_mod.range_read_tasks(n, p))


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    p = parallelism if parallelism > 0 else DataContext.get_current().read_parallelism
    p = max(1, min(p, len(items) or 1))
    chunks = np.array_split(np.arange(len(items)), p)

    def make(idx):
        subset = [items[i] for i in idx]
        return lambda: B.from_items(subset)

    return Dataset([make(c) for c in chunks if len(c)] or
                   [lambda: B.from_items([])])


def from_numpy(arrays: Union[np.ndarray, List[np.ndarray]],
               column: str = "data") -> Dataset:
    if isinstance(arrays, np.ndarray):
        arrays = [arrays]
    return Dataset([(lambda a=a: {column: a}) for a in arrays])


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    return Dataset([(lambda d=d: B.from_pandas(d)) for d in dfs])


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]

    def make(t):
        return lambda: {name: t.column(name).to_numpy(zero_copy_only=False)
                        for name in t.column_names}

    return Dataset([make(t) for t in tables])


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    return Dataset(ds_mod.parquet_read_tasks(paths, columns))


def read_csv(paths, **kwargs) -> Dataset:
    return Dataset(ds_mod.csv_read_tasks(paths, **kwargs))


def read_json(paths, *, lines: bool = True) -> Dataset:
    return Dataset(ds_mod.json_read_tasks(paths, lines=lines))


def read_numpy(paths, column: str = "data") -> Dataset:
    return Dataset(ds_mod.numpy_read_tasks(paths, column))


def read_text(paths, *, drop_empty_lines: bool = True) -> Dataset:
    return Dataset(ds_mod.text_read_tasks(paths, drop_empty_lines))


def read_binary_files(paths, *, include_paths: bool = False) -> Dataset:
    return Dataset(ds_mod.binary_read_tasks(paths, include_paths))


def read_sql(sql: str, connection_factory) -> Dataset:
    return Dataset(ds_mod.sql_read_tasks(sql, connection_factory))


def read_images(paths, *, size=None, mode: str = "RGB") -> Dataset:
    return Dataset(ds_mod.images_read_tasks(paths, size, mode))


def read_tfrecords(paths) -> Dataset:
    """tf.train.Example TFRecord shards, no tensorflow dependency
    (reference: ``data/datasource/tfrecords_datasource.py``)."""
    return Dataset(ds_mod.tfrecords_read_tasks(paths))


def read_webdataset(paths, *, decode: bool = True) -> Dataset:
    """WebDataset .tar shards: samples grouped by key, columns by extension
    (reference: ``data/datasource/webdataset_datasource.py``)."""
    return Dataset(ds_mod.webdataset_read_tasks(paths, decode=decode))


def from_huggingface(hf_dataset, *, parallelism: int = -1) -> Dataset:
    """A 🤗 ``datasets.Dataset`` (in-memory/arrow-backed) sliced into blocks
    (reference: ``data/read_api.py`` ``from_huggingface``)."""
    n = len(hf_dataset)
    if n == 0:
        return Dataset([lambda: {}])
    num_blocks = parallelism if parallelism > 0 else max(1, min(200, n // 1000 or 1))
    per = (n + num_blocks - 1) // num_blocks

    def make(lo, hi):
        def read():
            import numpy as np  # noqa: F401

            cols = hf_dataset[lo:hi]  # dict of lists
            return {k: _np_col(v) for k, v in cols.items()}

        return read

    def _np_col(v):
        import numpy as np

        try:
            return np.asarray(v)
        except Exception:  # ragged: keep as object array
            arr = np.empty(len(v), dtype=object)
            arr[:] = v
            return arr

    return Dataset([make(lo, min(lo + per, n))
                    for lo in builtins.range(0, n, per)] or [lambda: {}])


def from_torch(torch_dataset, *, parallelism: int = -1) -> Dataset:
    """A map-style ``torch.utils.data.Dataset`` sliced into blocks
    (reference: ``data/read_api.py`` ``from_torch``). Items become rows:
    dicts pass through, (x, y) tuples become {"item": x, "label": y},
    scalars/arrays become {"item": ...}."""
    n = len(torch_dataset)
    if n == 0:
        return Dataset([lambda: {}])
    num_blocks = parallelism if parallelism > 0 else max(1, min(64, n // 256 or 1))
    per = (n + num_blocks - 1) // num_blocks

    def to_row(item):
        import numpy as _np

        if isinstance(item, dict):
            return {k: _np.asarray(v) for k, v in item.items()}
        if isinstance(item, (tuple, list)) and len(item) == 2:
            return {"item": _np.asarray(item[0]),
                    "label": _np.asarray(item[1])}
        return {"item": _np.asarray(item)}

    def make(lo, hi):
        def read():
            return B.from_rows([to_row(torch_dataset[i])
                                for i in builtins.range(lo, hi)])

        return read

    return Dataset([make(lo, min(lo + per, n))
                    for lo in builtins.range(0, n, per)])
