"""Aggregations for groupby / global aggregate.

Reference analog: ``data/aggregate.py`` (AggregateFn: Count/Sum/Min/Max/
Mean/Std/Quantile) — implemented as vectorized numpy reductions over
hash-partitioned blocks.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional

import numpy as np

from ray_tpu.data import block as B


@dataclasses.dataclass
class AggregateFn:
    name: str
    on: Optional[str]
    reduce: Callable[[np.ndarray], float]

    def output_name(self) -> str:
        return f"{self.name}({self.on})" if self.on else self.name


def Count() -> AggregateFn:
    return AggregateFn("count", None, lambda v: len(v))


def Sum(on: str) -> AggregateFn:
    return AggregateFn("sum", on, np.sum)


def Min(on: str) -> AggregateFn:
    return AggregateFn("min", on, np.min)


def Max(on: str) -> AggregateFn:
    return AggregateFn("max", on, np.max)


def Mean(on: str) -> AggregateFn:
    return AggregateFn("mean", on, np.mean)


def Std(on: str) -> AggregateFn:
    return AggregateFn("std", on, lambda v: float(np.std(v, ddof=1)) if len(v) > 1 else 0.0)


def Quantile(on: str, q: float = 0.5) -> AggregateFn:
    return AggregateFn(f"quantile_{q}", on, lambda v: float(np.quantile(v, q)))


def aggregate_block(block: B.Block, key: Optional[str],
                    aggs: List[AggregateFn]) -> B.Block:
    """Aggregate one (hash-partitioned) block, optionally grouped by key."""
    n = B.num_rows(block)
    if key is None:
        if n == 0:
            return {}
        out = {}
        for agg in aggs:
            col = block[agg.on] if agg.on else np.arange(n)
            out[agg.output_name()] = np.asarray([agg.reduce(col)])
        return out
    if n == 0:
        return {}
    keys = block[key]
    uniq, inverse = np.unique(keys, return_inverse=True)
    out = {key: uniq}
    for agg in aggs:
        col = block[agg.on] if agg.on else np.arange(n)
        vals = [agg.reduce(col[inverse == i]) for i in range(len(uniq))]
        out[agg.output_name()] = np.asarray(vals)
    return out
