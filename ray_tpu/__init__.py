"""ray_tpu: a TPU-native distributed AI runtime.

A brand-new framework with the capability surface of the reference Ray
runtime (tasks, actors, a distributed object plane, topology-aware cluster
scheduling, and the library suite: data / train / tune / serve / rl), designed
TPU-first: collectives are XLA programs over ICI/DCN meshes, gang placement is
slice-aware, and every hot compute path is jit/pallas.
"""

from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID  # noqa: F401
from ray_tpu.core.actor import ActorClass, ActorHandle, method  # noqa: F401
from ray_tpu.core.api import RemoteFunction, remote  # noqa: F401
from ray_tpu.core.object_ref import ObjectRef  # noqa: F401
from ray_tpu.cluster.worker_core import ObjectRefGenerator  # noqa: F401
from ray_tpu.core.worker import (  # noqa: F401
    global_worker,
    init,
    is_initialized,
    shutdown,
)
from ray_tpu.runtime_context import get_runtime_context  # noqa: F401
from ray_tpu import exceptions  # noqa: F401


def timeline(filename=None):
    """Chrome-trace export of recent task spans (reference: ray.timeline)."""
    from ray_tpu.util.timeline import timeline as _tl

    return _tl(filename)


def memory_summary(**kwargs):
    """Cluster memory report: per-node store usage, the per-object owner
    table (with call sites under RT_RECORD_REF_CREATION_SITES=1), leak
    suspects and HBM stats (reference: ray.internal.memory_summary /
    `ray memory`). See `rt memory` for the CLI twin."""
    from ray_tpu.util.memory import memory_summary as _ms

    return _ms(**kwargs)

__version__ = "0.1.0"


def put(value):
    """Store ``value`` in the object plane; returns an ObjectRef."""
    return global_worker().put(value)


def get(refs, *, timeout=None):
    """Fetch the value(s) of ObjectRef(s), blocking until available."""
    return global_worker().get(refs, timeout)


def wait(refs, *, num_returns=1, timeout=None, fetch_local=True):
    """Block until ``num_returns`` of ``refs`` are ready."""
    return global_worker().wait(refs, num_returns, timeout)


def kill(actor, *, no_restart=True):
    """Forcibly terminate an actor."""
    from ray_tpu.core.actor import ActorHandle as _AH

    if not isinstance(actor, _AH):
        raise TypeError("kill() expects an ActorHandle")
    global_worker()._require_backend().kill_actor(actor._actor_id, no_restart)


def cancel(ref, *, force=False):
    """Request cancellation of the task that produces ``ref``."""
    global_worker()._require_backend().cancel(ref, force)


def internal_free(refs):
    """Eagerly delete objects from the object plane (reference:
    ``ray._private.internal_api.free``)."""
    if not isinstance(refs, (list, tuple)):
        refs = [refs]
    global_worker()._require_backend().free_objects(list(refs))


def get_actor(name, namespace=None):
    """Look up a named actor."""
    return global_worker()._require_backend().get_actor_handle(name, namespace)


def cluster_resources():
    return global_worker()._require_backend().cluster_resources()


def available_resources():
    return global_worker()._require_backend().available_resources()


def nodes():
    return global_worker()._require_backend().nodes()


__all__ = [
    "init", "shutdown", "is_initialized", "remote", "method", "put", "get",
    "wait", "kill", "cancel", "get_actor", "internal_free",
    "memory_summary",
    "cluster_resources",
    "available_resources", "nodes", "get_runtime_context", "ObjectRef",
    "ActorClass", "ActorHandle", "RemoteFunction", "exceptions",
]
