"""``rt`` — the cluster lifecycle CLI.

Reference analog: ``python/ray/scripts/scripts.py`` (``ray start/stop/status``)
— minus the cloud-provider plumbing (autoscaler handles provisioning).
Invoked as ``python -m ray_tpu.scripts.cli <cmd>`` (no pip install step).

  rt start --head [--port N] [--num-cpus N] [--num-tpus N]
  rt start --address=<gcs-host:port>      # join as a worker host
  rt status
  rt stop
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ray_tpu._private.config import get_config
from ray_tpu.cluster import node_main


def _list_node_states() -> List[Dict]:
    out = []
    d = node_main.state_dir()
    try:
        names = os.listdir(d)
    except FileNotFoundError:
        return out
    for name in names:
        try:
            with open(os.path.join(d, name)) as f:
                out.append(json.load(f))
        except (ValueError, FileNotFoundError):
            pass
    return out


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except (ProcessLookupError, PermissionError):
        return False


def cmd_start(args: argparse.Namespace) -> int:
    daemon_args = [sys.executable, "-m", "ray_tpu.cluster.node_main"]
    if args.head:
        daemon_args += ["--head", "--host", args.host, "--port",
                        str(args.port)]
        if args.session_name:
            daemon_args += ["--session-name", args.session_name]
    else:
        daemon_args += ["--address", args.address]
    if args.num_cpus is not None:
        daemon_args += ["--num-cpus", str(args.num_cpus)]
    if args.num_tpus is not None:
        daemon_args += ["--num-tpus", str(args.num_tpus)]
    if args.resources:
        daemon_args += ["--resources", args.resources]

    log_dir = os.path.join(get_config().session_dir_root, "logs")
    os.makedirs(log_dir, exist_ok=True)
    log_path = os.path.join(log_dir, f"node-{int(time.time())}-{os.getpid()}.log")
    log_file = open(log_path, "ab")
    proc = subprocess.Popen(
        daemon_args, stdout=subprocess.PIPE, stderr=log_file,
        start_new_session=True)  # detach: survives this CLI process
    log_file.close()

    # Block until the daemon prints its ready line (or dies).
    deadline = time.monotonic() + args.timeout
    state = None
    while time.monotonic() < deadline:
        line = proc.stdout.readline().decode()
        if not line:
            break
        if line.startswith("RT_NODE_READY "):
            state = json.loads(line[len("RT_NODE_READY "):])
            break
    if state is None:
        rc = proc.poll()
        print(f"rt start: node daemon failed to come up "
              f"(rc={rc}); log: {log_path}", file=sys.stderr)
        return 1
    role = "head" if state["head"] else "worker"
    print(f"started {role} node {state['node_id'][:8]} pid={state['pid']}")
    print(f"  gcs_address:    {state['gcs_address']}")
    print(f"  raylet_address: {state['raylet_address']}")
    print(f"  session:        {state['session_name']}")
    if state["head"]:
        print(f"\njoin another host with:\n"
              f"  rt start --address={state['gcs_address']}\n"
              f"attach a driver with:\n"
              f"  ray_tpu.init(address=\"{state['gcs_address']}\")")
    return 0


def cmd_stop(args: argparse.Namespace) -> int:
    states = _list_node_states()
    if not states:
        print("no running nodes found")
        return 0
    # workers first, head last — workers need the GCS to deregister
    states.sort(key=lambda s: s["head"])
    stopped = 0
    for st in states:
        pid = st["pid"]
        if not _pid_alive(pid):
            _cleanup_state(st)
            continue
        try:
            os.kill(pid, signal.SIGTERM)
            stopped += 1
        except ProcessLookupError:
            pass
    deadline = time.monotonic() + args.timeout
    while time.monotonic() < deadline:
        if not any(_pid_alive(s["pid"]) for s in states):
            break
        time.sleep(0.1)
    for st in states:
        if args.force and _pid_alive(st["pid"]):
            try:
                os.kill(st["pid"], signal.SIGKILL)
            except ProcessLookupError:
                pass
        _cleanup_state(st)
    print(f"stopped {stopped} node(s)")
    return 0


def _cleanup_state(st: Dict) -> None:
    for path in (os.path.join(node_main.state_dir(), f"{st['node_id']}.json"),):
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
    if st.get("head"):
        latest = node_main.read_session_latest()
        if latest and latest.get("node_id") == st["node_id"]:
            try:
                os.unlink(node_main.session_latest_path())
            except FileNotFoundError:
                pass


def _gcs_call(address: str, method: str, payload: Dict) -> Dict:
    from ray_tpu.cluster.rpc import RpcClient

    async def _go():
        client = RpcClient(address, peer_id="rt-cli")
        await client.connect()
        try:
            return await client.call(method, payload, timeout=10.0)
        finally:
            await client.close()

    return asyncio.run(_go())


def _resolve_gcs(address: Optional[str]) -> Optional[str]:
    if address and address not in ("auto",):
        return address
    latest = node_main.read_session_latest()
    return latest["gcs_address"] if latest else None


def cmd_status(args: argparse.Namespace) -> int:
    gcs = _resolve_gcs(args.address)
    if gcs is None:
        print("no running cluster found (no session_latest.json; "
              "pass --address)", file=sys.stderr)
        return 1
    try:
        nodes = _gcs_call(gcs, "list_nodes", {})
    except Exception as e:
        print(f"cannot reach GCS at {gcs}: {e!r}", file=sys.stderr)
        return 1
    print(f"cluster at {gcs}: {sum(n['alive'] for n in nodes)} alive / "
          f"{len(nodes)} total nodes")
    for n in nodes:
        state = "ALIVE" if n["alive"] else "DEAD"
        role = n.get("labels", {}).get("node_role", "worker")
        print(f"  {n['node_id'][:8]} {state:5} {role:6} {n['address']:>21} "
              f"total={n['resources']} available={n['available']}")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="rt")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_start = sub.add_parser("start", help="start a head or worker node")
    p_start.add_argument("--head", action="store_true")
    p_start.add_argument("--address", default=None)
    p_start.add_argument("--host", default="127.0.0.1")
    p_start.add_argument("--port", type=int, default=0)
    p_start.add_argument("--num-cpus", type=float, default=None)
    p_start.add_argument("--num-tpus", type=float, default=None)
    p_start.add_argument("--resources", default=None)
    p_start.add_argument("--session-name", default=None)
    p_start.add_argument("--timeout", type=float, default=30.0)
    p_start.set_defaults(fn=cmd_start)

    p_stop = sub.add_parser("stop", help="stop all nodes on this machine")
    p_stop.add_argument("--force", action="store_true")
    p_stop.add_argument("--timeout", type=float, default=10.0)
    p_stop.set_defaults(fn=cmd_stop)

    p_status = sub.add_parser("status", help="show cluster nodes")
    p_status.add_argument("--address", default=None)
    p_status.set_defaults(fn=cmd_status)

    args = parser.parse_args(argv)
    if args.cmd == "start" and not args.head and not args.address:
        parser.error("rt start needs --head or --address=<gcs>")
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
