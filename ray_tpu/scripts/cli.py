"""``rt`` — the cluster lifecycle CLI.

Reference analog: ``python/ray/scripts/scripts.py`` (``ray start/stop/status``)
— minus the cloud-provider plumbing (autoscaler handles provisioning).
Invoked as ``python -m ray_tpu.scripts.cli <cmd>`` (no pip install step).

  rt start --head [--port N] [--num-cpus N] [--num-tpus N]
  rt start --address=<gcs-host:port>      # join as a worker host
  rt status
  rt stop
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ray_tpu._private.config import get_config
from ray_tpu.cluster import node_main


def _list_node_states() -> List[Dict]:
    out = []
    d = node_main.state_dir()
    try:
        names = os.listdir(d)
    except FileNotFoundError:
        return out
    for name in names:
        try:
            with open(os.path.join(d, name)) as f:
                out.append(json.load(f))
        except (ValueError, FileNotFoundError):
            pass
    return out


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except (ProcessLookupError, PermissionError):
        return False


def cmd_start(args: argparse.Namespace) -> int:
    daemon_args = [sys.executable, "-m", "ray_tpu.cluster.node_main",
                   "--host", args.host]
    if args.head:
        daemon_args += ["--head", "--port", str(args.port)]
        if args.session_name:
            daemon_args += ["--session-name", args.session_name]
    else:
        daemon_args += ["--address", args.address]
    if args.num_cpus is not None:
        daemon_args += ["--num-cpus", str(args.num_cpus)]
    if args.num_tpus is not None:
        daemon_args += ["--num-tpus", str(args.num_tpus)]
    if args.resources:
        daemon_args += ["--resources", args.resources]

    log_dir = os.path.join(get_config().session_dir_root, "logs")
    os.makedirs(log_dir, exist_ok=True)
    log_path = os.path.join(log_dir, f"node-{int(time.time())}-{os.getpid()}.log")
    log_file = open(log_path, "ab")
    proc = subprocess.Popen(
        daemon_args, stdout=subprocess.PIPE, stderr=log_file,
        start_new_session=True)  # detach: survives this CLI process
    log_file.close()

    # Block until the daemon prints its ready line (or dies) — readline
    # gated by select so --timeout holds even if the daemon never writes.
    import select

    deadline = time.monotonic() + args.timeout
    state = None
    buf = b""
    while time.monotonic() < deadline:
        ready, _, _ = select.select([proc.stdout], [], [],
                                    max(0.0, deadline - time.monotonic()))
        if not ready:
            break
        chunk = os.read(proc.stdout.fileno(), 4096)
        if not chunk:
            break
        buf += chunk
        # only parse COMPLETE lines — the ready json may straddle a read
        complete, _, buf = buf.rpartition(b"\n")
        for line in complete.decode(errors="replace").splitlines():
            if line.startswith("RT_NODE_READY "):
                state = json.loads(line[len("RT_NODE_READY "):])
                break
        if state is not None:
            break
    if state is None:
        rc = proc.poll()
        if rc is None:
            proc.terminate()  # half-started daemon: don't leave it dangling
        print(f"rt start: node daemon failed to come up "
              f"(rc={rc}); log: {log_path}", file=sys.stderr)
        return 1
    role = "head" if state["head"] else "worker"
    print(f"started {role} node {state['node_id'][:8]} pid={state['pid']}")
    print(f"  gcs_address:    {state['gcs_address']}")
    print(f"  raylet_address: {state['raylet_address']}")
    print(f"  session:        {state['session_name']}")
    if state["head"]:
        print(f"\njoin another host with:\n"
              f"  rt start --address={state['gcs_address']}\n"
              f"attach a driver with:\n"
              f"  ray_tpu.init(address=\"{state['gcs_address']}\")")
    return 0


def cmd_stop(args: argparse.Namespace) -> int:
    states = _list_node_states()
    if not states:
        print("no running nodes found")
        return 0
    # workers first, head last — workers need the GCS to deregister
    states.sort(key=lambda s: s["head"])
    stopped = 0
    for st in states:
        pid = st["pid"]
        if not _pid_alive(pid):
            _cleanup_state(st)
            continue
        try:
            os.kill(pid, signal.SIGTERM)
            stopped += 1
        except ProcessLookupError:
            pass
    deadline = time.monotonic() + args.timeout
    while time.monotonic() < deadline:
        if not any(_pid_alive(s["pid"]) for s in states):
            break
        time.sleep(0.1)
    for st in states:
        if args.force and _pid_alive(st["pid"]):
            try:
                os.kill(st["pid"], signal.SIGKILL)
            except ProcessLookupError:
                pass
        _cleanup_state(st)
    print(f"stopped {stopped} node(s)")
    return 0


def _cleanup_state(st: Dict) -> None:
    for path in (os.path.join(node_main.state_dir(), f"{st['node_id']}.json"),):
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
    if st.get("head"):
        latest = node_main.read_session_latest()
        if latest and latest.get("node_id") == st["node_id"]:
            try:
                os.unlink(node_main.session_latest_path())
            except FileNotFoundError:
                pass


def _gcs_call(address: str, method: str, payload: Dict) -> Dict:
    from ray_tpu.cluster.rpc import RpcClient

    async def _go():
        client = RpcClient(address, peer_id="rt-cli")
        await client.connect()
        try:
            return await client.call(method, payload, timeout=10.0)
        finally:
            await client.close()

    return asyncio.run(_go())


def _resolve_gcs(address: Optional[str]) -> Optional[str]:
    if address and address not in ("auto",):
        return address
    latest = node_main.read_session_latest()
    return latest["gcs_address"] if latest else None


def cmd_status(args: argparse.Namespace) -> int:
    gcs = _resolve_gcs(args.address)
    if gcs is None:
        print("no running cluster found (no session_latest.json; "
              "pass --address)", file=sys.stderr)
        return 1
    try:
        nodes = _gcs_call(gcs, "list_nodes", {})
    except Exception as e:
        print(f"cannot reach GCS at {gcs}: {e!r}", file=sys.stderr)
        return 1
    print(f"cluster at {gcs}: {sum(n['alive'] for n in nodes)} alive / "
          f"{len(nodes)} total nodes")
    for n in nodes:
        state = "ALIVE" if n["alive"] else "DEAD"
        role = n.get("labels", {}).get("node_role", "worker")
        print(f"  {n['node_id'][:8]} {state:5} {role:6} {n['address']:>21} "
              f"total={n['resources']} available={n['available']}")
        # scheduling plane (heartbeat sched summary): per-class queue
        # depth + warm-pool occupancy/hit-rate — the overload story at a
        # glance (which class is deep, whether dispatch pays cold boots)
        sched = n.get("sched") or {}
        warm = sched.get("warm") or {}
        if warm:
            hits = warm.get("warm_hits", 0)
            cold = warm.get("cold_spawns", 0)
            rate = (f"{100.0 * hits / (hits + cold):.0f}%"
                    if hits + cold else "n/a")
            extras = []
            if warm.get("actor_adoptions"):
                extras.append(f"{warm['actor_adoptions']} actor adoption(s)")
            if sched.get("backpressure_total"):
                extras.append(
                    f"{sched['backpressure_total']} backpressured")
            if sched.get("deadline_evictions_total"):
                extras.append(f"{sched['deadline_evictions_total']} "
                              f"deadline-evicted")
            print(f"           warm pool: {warm.get('idle', 0)} idle / "
                  f"floor {warm.get('floor', 0)}, warm-hit rate {rate} "
                  f"({hits} warm / {cold} cold)"
                  + (f"; {', '.join(extras)}" if extras else ""))
        classes = sched.get("classes") or []
        if classes:
            desc = ", ".join(
                f"{c.get('class')}:{c.get('depth')}"
                + (f" (p99 {c['wait_p99_s']}s)"
                   if c.get("wait_p99_s") is not None else "")
                for c in classes[:5])
            print(f"           queued by class: {desc}")
    return 0


def _attach_driver(address: Optional[str]):
    import ray_tpu

    gcs = _resolve_gcs(address)
    if gcs is None:
        print("no running cluster found (pass --address or start one with "
              "`rt start --head`)", file=sys.stderr)
        raise SystemExit(1)
    ray_tpu.init(address=gcs, ignore_reinit_error=True)
    return ray_tpu


def cmd_job(args: argparse.Namespace) -> int:
    from ray_tpu import job as rt_job

    rt = _attach_driver(args.address)
    try:
        if args.job_cmd == "submit":
            import shlex

            parts = list(args.entrypoint or [])
            if parts and parts[0] == "--":
                parts = parts[1:]  # only the leading separator
            entrypoint = " ".join(shlex.quote(p) for p in parts)
            if not entrypoint:
                print("rt job submit: empty entrypoint", file=sys.stderr)
                return 1
            env_vars = dict(kv.split("=", 1) for kv in (args.env or []))
            job_id = rt_job.submit_job(entrypoint, env_vars=env_vars)
            print(job_id)
            if args.wait:
                return _follow_job(rt_job, job_id, from_start=True)
            return 0
        if args.job_cmd == "status":
            meta = rt_job.job_status(args.job_id)
            print(json.dumps(meta, indent=2))
            return 0 if meta["status"] in ("RUNNING", "SUCCEEDED", "PENDING") \
                else 1
        if args.job_cmd == "logs":
            if args.follow:
                return _follow_job(rt_job, args.job_id, from_start=True)
            print(rt_job.tail_job_logs(args.job_id)["data"], end="")
            return 0
        if args.job_cmd == "stop":
            print("stopped" if rt_job.stop_job(args.job_id)
                  else "already finished")
            return 0
        if args.job_cmd == "list":
            for meta in rt_job.list_jobs():
                print(f"{meta['job_id']}  {meta['status']:9}  "
                      f"{meta.get('entrypoint', '')}")
            return 0
        return 1
    finally:
        rt.shutdown()


def _follow_job(rt_job, job_id: str, from_start: bool = False) -> int:
    offset = 0
    while True:
        chunk = rt_job.tail_job_logs(job_id, offset)
        if chunk["data"]:
            print(chunk["data"], end="", flush=True)
        offset = chunk["next_offset"]
        if chunk["done"]:
            break
        time.sleep(0.3)
    status = rt_job.job_status(job_id)["status"]
    print(f"\n--- job {job_id}: {status}", file=sys.stderr)
    return 0 if status == "SUCCEEDED" else 1


_LIST_RPCS = {"nodes": "list_nodes", "actors": "list_actors",
              "placement-groups": "list_placement_groups",
              "tasks": "list_tasks", "objects": "list_objects",
              "errors": "list_failure_events"}


def cmd_list(args: argparse.Namespace) -> int:
    gcs = _resolve_gcs(args.address)
    if gcs is None:
        print("no running cluster found (pass --address)", file=sys.stderr)
        return 1
    if args.what == "jobs":
        return cmd_job(argparse.Namespace(address=args.address,
                                          job_cmd="list"))
    rows = _gcs_call(gcs, _LIST_RPCS[args.what], {"limit": args.limit})
    print(json.dumps(rows, indent=2, default=str))
    return 0


def cmd_serve(args: argparse.Namespace) -> int:
    """rt serve deploy/status/shutdown (reference: ``serve/scripts.py``)."""
    from ray_tpu import serve
    from ray_tpu.serve import schema

    _attach_driver(args.address)
    if args.serve_cmd == "deploy":
        sys.path.insert(0, os.getcwd())  # import_path resolves from cwd
        names = schema.deploy_config(schema.load_config_file(args.config))
        for n in names:
            print(f"deployed application {n!r}")
        return 0
    if args.serve_cmd == "status":
        if getattr(args, "json", False):
            print(json.dumps(serve.detailed_status(), indent=2, default=str))
            return 0
        st = serve.detailed_status()
        apps = st.get("applications", {})
        if not apps:
            # the decision log outlives the apps it scaled (post-mortem of
            # a deleted deployment) — only the non-verbose view can stop
            print("no serve applications")
            if not getattr(args, "verbose", False):
                return 0
        proxies = st.get("proxies") or []
        if len(proxies) > 1:
            print("proxies: " + ", ".join(
                f"{p.get('proxy')}:{p.get('port')}" for p in proxies))
        for app, meta in apps.items():
            print(f"app {app!r}  route={meta.get('route_prefix')}  "
                  f"ingress={meta.get('ingress')}")
            for name, d in (meta.get("deployments") or {}).items():
                s = d.get("stats") or {}
                cb = (f"  slots {s['cb_active']}/{s['cb_slots']}"
                      f"  tokens {s.get('cb_tokens_generated', 0)}"
                      f"  completed {s.get('cb_requests_completed', 0)}"
                      if "cb_slots" in s else "")
                if "kv_hit_rate" in s:
                    cb += (f"  kv {100 * s['kv_hit_rate']:.0f}%"
                           f" {s.get('kv_bytes', 0) / 1e6:.1f}MB")
                if "eng_ttft_att" in s:
                    # engine flight-recorder rollup: SLO attainment +
                    # goodput + worst decode tick-gap across the fleet
                    cb += (f"  slo {s['eng_ttft_att']:.2f}/"
                           f"{s['eng_tpot_att']:.2f}"
                           f"  goodput {s.get('eng_goodput_tok_s', 0):.0f}"
                           f"tok/s"
                           f"  gap {1e3 * s.get('eng_gap_p99_s', 0):.0f}ms")
                print(f"  {name:<24} replicas {d.get('replicas', 0)}/"
                      f"{d.get('target', 0)}"
                      f"{' (+%d starting)' % d['starting'] if d.get('starting') else ''}"
                      f"  ongoing {s.get('ongoing', 0)}"
                      f"  queue {s.get('queue_depth', 0)}"
                      f"{cb}"
                      f"  p50 {1e3 * (s.get('p50_s') or 0):.1f}ms"
                      f"  p99 {1e3 * (s.get('p99_s') or 0):.1f}ms"
                      f"  qps {s.get('qps', 0)}")
        if getattr(args, "verbose", False):
            decisions = st.get("decisions") or []
            print(f"\nautoscaler decisions ({len(decisions)} recent):")
            for d in decisions:
                trig = d.get("trigger") or {}
                hyst = trig.get("hysteresis")
                when = time.strftime("%H:%M:%S",
                                     time.localtime(d.get("t", 0)))
                line = (f"  [{when}] {d['app']}/{d['deployment']} "
                        f"target {d.get('old_target')} -> "
                        f"{d.get('new_target')} ({d.get('direction')}; "
                        f"signal={trig.get('signal', 'ongoing')} "
                        f"ongoing_avg={trig.get('ongoing_avg', 0)} "
                        f"queue={trig.get('queue_depth', 0)} "
                        f"p99={1e3 * (trig.get('p99_s') or 0):.1f}ms "
                        f"qps={trig.get('qps', 0)})")
                if hyst:
                    line += (f" [held {hyst.get('held_s')}s of "
                             f"{hyst.get('delay_s')}s]")
                print(line)
        return 0
    if args.serve_cmd == "shutdown":
        serve.shutdown()
        print("serve stopped")
        return 0
    return 1


def cmd_rl(args: argparse.Namespace) -> int:
    """rt rl train/evaluate (reference: ``rllib/train.py``,
    ``rllib/evaluate.py``)."""
    import ray_tpu
    from ray_tpu.rl import train as rl_train

    if args.rl_cmd == "examples":  # pure listing: no cluster needed
        for name in rl_train.list_tuned_examples():
            print(name)
        return 0
    if args.rl_cmd == "rlhf":
        return _run_rlhf(args)
    if args.rl_cmd == "train" and not args.run \
            and not getattr(args, "file", None):
        print("rt rl train: pass --run ALGO or -f TUNED_EXAMPLE",
              file=sys.stderr)
        return 2
    if args.rl_cmd == "train" and getattr(args, "file", None) \
            and (args.run or args.env or args.config or args.config_file):
        # a tuned example fully specifies algo/env/config; silently
        # training something other than what the flag says would mislead
        print("rt rl train: -f is exclusive with --run/--env/--config/"
              "--config-file (stop flags still apply)", file=sys.stderr)
        return 2
    owns_session = False
    if args.address:
        _attach_driver(args.address)
        owns_session = True
    elif not ray_tpu.is_initialized():
        ray_tpu.init()  # standalone local cluster, like `rllib train`
        owns_session = True
    try:
        if args.rl_cmd == "train":
            if getattr(args, "file", None):
                rl_train.run_tuned_example(
                    args.file, checkpoint_dir=args.checkpoint_dir,
                    stop_iters=args.stop_iters,
                    stop_reward=args.stop_reward,
                    stop_timesteps=args.stop_timesteps)
                return 0
            rl_train.run_train(
                args.run, env=args.env, config_json=args.config,
                config_file=args.config_file,
                stop_iters=(args.stop_iters if args.stop_iters is not None
                            else 10),
                stop_reward=args.stop_reward,
                stop_timesteps=args.stop_timesteps,
                checkpoint_dir=args.checkpoint_dir)
            return 0
        if args.rl_cmd == "evaluate":
            rl_train.run_evaluate(args.checkpoint, run=args.run,
                                  episodes=args.episodes)
            return 0
        return 1
    finally:
        if owns_session:  # don't tear down a borrowed live session
            ray_tpu.shutdown()


def _run_rlhf(args: argparse.Namespace) -> int:
    """rt rl rlhf: the end-to-end RLHF pipeline (placed policy /
    reference / reward / generation roles, ContinuousEngine generate
    phase, streamed weight sync) for N iterations, one JSON line per
    iteration. The printed trace id replays the placement + phase story
    through `rt trace <id>`."""
    import json as _json

    import ray_tpu
    from ray_tpu.rl.rlhf import RLHFPipeline

    owns_session = False
    if args.address:
        _attach_driver(args.address)
        owns_session = True
    elif not ray_tpu.is_initialized():
        # a standalone session must be able to reserve the four
        # one-CPU role bundles even on a small box (init()'s default
        # CPU count is the machine's core count — 1 in CI)
        ray_tpu.init(num_cpus=6)
        owns_session = True
    pipeline = None
    try:
        pipeline = RLHFPipeline(
            preset=args.preset, num_prompts=args.prompts,
            prompt_len=args.prompt_len, max_new_tokens=args.max_new,
            max_slots=args.slots, seed=args.seed)
        print(f"rlhf: roles placed "
              f"({', '.join(r['role'] for r in pipeline.group.describe())})"
              f"; trace {pipeline.trace_id}", flush=True)
        for _ in range(args.iters):
            print(_json.dumps(pipeline.run_iteration()), flush=True)
        s = pipeline.stats()
        print(f"rlhf: {s['iterations']} iteration(s), "
              f"{s['tokens_generated']} tokens generated, "
              f"{s['sync_bytes_total']} weight-sync bytes; "
              f"rt trace {s['trace_id']} shows the placement story")
        return 0
    finally:
        if pipeline is not None:
            pipeline.shutdown()
        if owns_session:
            ray_tpu.shutdown()


def cmd_trace(args: argparse.Namespace) -> int:
    """rt trace <task_id|trace_id|span_id>: print the span tree with the
    per-phase latency tables and the named critical path (the cluster-side
    twin of `rt profile` — reads the GCS task-event store directly, no
    driver attach)."""
    from ray_tpu.util.tracing import format_trace

    gcs = _resolve_gcs(args.address)
    if gcs is None:
        print("no running cluster found (pass --address)", file=sys.stderr)
        return 1
    try:
        events = _gcs_call(gcs, "list_tasks",
                           {"limit": args.limit, "serve": "include"})
    except Exception as e:  # noqa: BLE001 — one line, not a stack trace
        print(f"rt trace: cannot reach GCS at {gcs}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 1
    ident = args.id

    def ctx(e):
        return e.get("trace") or {}

    trace_id = None
    if any(ctx(e).get("trace_id") == ident for e in events):
        trace_id = ident
    else:
        for e in events:
            if (e.get("task_id", "").startswith(ident)
                    or ctx(e).get("span_id") == ident):
                trace_id = ctx(e).get("trace_id")
                if trace_id is None:
                    # untraced task: still print its event (+ phases if the
                    # task ran with phase tracing from an ambient span)
                    print(format_trace([e]))
                    return 0
                break
    if trace_id is None:
        print(f"rt trace: no task or trace matching {ident!r} in the "
              f"event store (traces are bounded; re-run with tracing on)",
              file=sys.stderr)
        return 1
    spans = [e for e in events if ctx(e).get("trace_id") == trace_id]
    print(format_trace(spans))
    return 0


def cmd_memory(args: argparse.Namespace) -> int:
    """rt memory: the byte-side twin of `rt trace` (reference: `ray
    memory` + memory_summary). Default: per-node store usage + per-object
    owner tables + leak suspects; --oom replays OOM post-mortems straight
    from the GCS (no driver attach); --device adds the HBM table."""
    from ray_tpu.util.memory import format_oom_reports

    if args.oom:
        gcs = _resolve_gcs(args.address)
        if gcs is None:
            print("no running cluster found (pass --address)",
                  file=sys.stderr)
            return 1
        try:
            events = _gcs_call(gcs, "list_mem_events",
                               {"kind": "oom_kill", "limit": args.limit})
        except Exception as e:  # noqa: BLE001 — one line, no stack trace
            print(f"rt memory: cannot reach GCS at {gcs}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 1
        if args.id:
            # filter to one victim / object / node; an unknown or expired
            # id gets a clear one-liner + nonzero, never an empty table
            ident = args.id
            events = [
                ev for ev in events
                if str((ev.get("victim") or {}).get("worker_id", ""))
                .startswith(ident)
                or str(ev.get("node_id", "")).startswith(ident)
                or any(str(o.get("oid", "")).startswith(ident)
                       or str(o.get("oid", "")).endswith(ident)
                       for o in ev.get("top_objects") or ())]
            if not events:
                print(f"rt memory --oom: no OOM post-mortem matching "
                      f"{ident!r} (the event store is bounded — it may "
                      f"have expired)", file=sys.stderr)
                return 1
        print(format_oom_reports(events))
        return 0
    if args.id:
        print("rt memory: an id filter only applies with --oom",
              file=sys.stderr)
        return 2
    rt = _attach_driver(args.address)
    try:
        print(rt.memory_summary(limit=args.limit, top_n=args.top,
                                leak_age_s=args.leak_age,
                                include_devices=args.device))
        return 0
    finally:
        rt.shutdown()


def cmd_errors(args: argparse.Namespace) -> int:
    """rt errors: tail/filter the cluster's categorized FailureEvent feed
    (cluster/gcs.py failure_events store — the death-cause taxonomy of
    core/failure.py). Reads the GCS directly, no driver attach."""
    gcs = _resolve_gcs(args.address)
    if gcs is None:
        print("no running cluster found (pass --address)", file=sys.stderr)
        return 1
    payload = {"limit": args.limit}
    if args.category:
        payload["category"] = args.category
    if getattr(args, "origin", None):
        # "chaos" = injected by the chaos plane; "organic" = everything else
        payload["origin"] = args.origin
    try:
        events = _gcs_call(gcs, "list_failure_events", payload)
    except Exception as e:  # noqa: BLE001 — one line, no stack trace
        print(f"rt errors: cannot reach GCS at {gcs}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(events, indent=2, default=str))
        return 0
    if not events:
        what = (f"category {args.category!r}" if args.category
                else "any category")
        print(f"(no failure events recorded for {what})")
        return 0
    for ev in events:
        # last_t: a deduped crash loop shows when it LAST fired, like the
        # dashboard — not the 30s-old first occurrence
        when = time.strftime("%H:%M:%S", time.localtime(
            ev.get("last_t", ev.get("t", 0))))
        who = " ".join(
            f"{k}={str(ev[k])[:12]}" for k in
            ("name", "task_id", "actor_id", "worker_id") if ev.get(k))
        count = f" x{ev['count']}" if ev.get("count", 1) > 1 else ""
        origin = f"[{ev['origin']}] " if ev.get("origin") else ""
        print(f"{when}  {str(ev.get('node_id', '?'))[:8]:<8} "
              f"{ev.get('category', 'unknown'):<24}{count:<5} "
              f"{origin}{who + '  ' if who else ''}{ev.get('message', '')}")
    return 0


def cmd_sched(args: argparse.Namespace) -> int:
    """rt sched decisions/balance: the placement-receipt plane — every
    scheduling decision's record (kind, chosen node, reason, candidate
    feature vectors; GCS placement_events store) and the cross-node
    queued+running balance snapshot behind rt_sched_node_imbalance.
    Reads the GCS directly, no driver attach."""
    kinds = ("dispatch_local", "spillback", "actor_place", "pg_place",
             "warm_adopt", "gang_place")
    if (args.sched_cmd == "decisions" and args.kind
            and args.kind not in kinds):
        # local usage errors must not masquerade as cluster unreachability
        print(f"rt sched decisions: unknown --kind {args.kind!r} "
              f"(one of: {', '.join(kinds)})", file=sys.stderr)
        return 2
    gcs = _resolve_gcs(args.address)
    if gcs is None:
        print("rt sched: no running cluster found (pass --address)",
              file=sys.stderr)
        return 1
    try:
        if args.sched_cmd == "balance":
            reply = _gcs_call(gcs, "sched_balance", {})
            if args.json:
                print(json.dumps(reply, indent=2, default=str))
                return 0
            print(f"cross-node imbalance (CoV of queued+running load): "
                  f"{reply.get('cov', 0.0):.3f}")
            for row in reply.get("nodes") or ():
                print(f"  {str(row.get('node_id', '?'))[:8]:<8} "
                      f"queued={row.get('queued', 0):<6} "
                      f"running={row.get('running', 0):<6} "
                      f"load={row.get('load', 0)}")
            hist = reply.get("history") or []
            if hist:
                series = " ".join(f"{h.get('cov', 0.0):.2f}"
                                  for h in hist[-10:])
                print(f"recent ticks: {series}")
            return 0
        # decisions
        payload: Dict = {"limit": args.limit}
        if args.kind:
            payload["kind"] = args.kind
        if args.node:
            payload["node"] = args.node
        events = _gcs_call(gcs, "list_placement_events", payload)
        if args.json:
            print(json.dumps(events, indent=2, default=str))
            return 0
        if not events:
            what = f"kind {args.kind!r}" if args.kind else "any kind"
            print(f"(no placement decisions recorded for {what})")
            return 0
        for ev in events:
            when = time.strftime("%H:%M:%S", time.localtime(
                ev.get("last_t", ev.get("t", 0))))
            who = " ".join(
                f"{k}={str(ev[k])[:12]}" for k in
                ("name", "task_id", "actor_id", "pg_id") if ev.get(k))
            count = f" x{ev['count']}" if ev.get("count", 1) > 1 else ""
            hop = ""
            if ev.get("kind") == "spillback":
                hop = (f" {str(ev.get('from_node', '?'))[:8]}"
                       f"->{str(ev.get('node_id', '?'))[:8]}"
                       f" hops={ev.get('hops', 1)}")
            print(f"{when}  {str(ev.get('node_id', '?'))[:8]:<8} "
                  f"{ev.get('kind', '?'):<15}{count:<7} "
                  f"reason={ev.get('reason', '?'):<20}"
                  f"{hop} {who}  "
                  f"candidates={len(ev.get('candidates') or ())}")
        return 0
    except Exception as e:  # noqa: BLE001 — one line, no stack trace
        print(f"rt sched: cannot reach GCS at {gcs}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 1


def cmd_chaos(args: argparse.Namespace) -> int:
    """rt chaos arm/disarm/status: drive the fault-injection plane
    (util/chaos.py) against a live cluster. The plan ships through the GCS
    KV (@chaos/plan) and a revision on every heartbeat reply — raylets arm
    themselves and their workers within a heartbeat."""
    gcs = _resolve_gcs(args.address)
    if gcs is None:
        print("rt chaos: no running cluster found (pass --address)",
              file=sys.stderr)
        return 1
    if args.chaos_cmd == "arm" and args.plan:
        # local usage errors must not masquerade as cluster unreachability
        try:
            with open(args.plan) as f:
                plan_from_file = json.load(f)
        except (OSError, ValueError) as e:
            print(f"rt chaos arm: cannot read plan file {args.plan!r}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2
    try:
        if args.chaos_cmd == "arm":
            if args.plan:
                plan = plan_from_file
            else:
                if not args.site:
                    print("rt chaos arm: pass --plan FILE or --site SITE",
                          file=sys.stderr)
                    return 2
                fault: Dict = {"site": args.site}
                for flag, field in (("at", "at"), ("after", "after"),
                                    ("prob", "prob"),
                                    ("max_fires", "max_fires"),
                                    ("delay", "delay_s"),
                                    ("value", "value"),
                                    ("target", "target")):
                    v = getattr(args, flag)
                    if v is not None:
                        fault[field] = v
                plan = {"seed": args.seed, "faults": [fault]}
            reply = _gcs_call(gcs, "chaos_arm", {"plan": plan})
            if reply.get("error"):
                print(f"rt chaos arm: {reply['error']}", file=sys.stderr)
                return 1
            faults = reply.get("plan", {}).get("faults", [])
            print(f"chaos armed (rev {reply.get('rev')}): "
                  f"{len(faults)} fault(s) at "
                  f"{', '.join(f['site'] for f in faults)}")
            return 0
        if args.chaos_cmd == "disarm":
            reply = _gcs_call(gcs, "chaos_disarm", {})
            print(f"chaos disarmed (rev {reply.get('rev')})")
            return 0
        if args.chaos_cmd == "status":
            print(json.dumps(_gcs_call(gcs, "chaos_status", {}),
                             indent=2, default=str))
            return 0
        return 1
    except Exception as e:  # noqa: BLE001 — one line, no stack trace
        print(f"rt chaos: cannot reach GCS at {gcs}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 1


def cmd_doctor(args: argparse.Namespace) -> int:
    """rt doctor: one-shot cluster health report (util/doctor.py) — node/
    actor/worker liveness, recent failure categories ranked, OOM
    post-mortems + leak suspects from the memory plane, queue-depth and
    spill pressure. Exit 0 healthy / 1 unhealthy / 2 unreachable."""
    from ray_tpu.util import doctor

    gcs = _resolve_gcs(args.address)
    if gcs is None:
        print("rt doctor: no running cluster found (pass --address)",
              file=sys.stderr)
        return 2
    text, rc = doctor.run(gcs, window_s=args.window,
                          queue_warn=args.queue_warn,
                          queue_wait_warn_s=args.queue_wait_warn,
                          serve_p99_warn_s=args.serve_p99_warn,
                          imbalance_warn=args.imbalance_warn,
                          tick_gap_warn_s=args.tick_gap_warn,
                          slo_warn=args.slo_warn,
                          bubble_warn=args.bubble_warn,
                          launch_gap_warn_s=args.launch_gap_warn,
                          data_wait_warn=args.data_wait_warn,
                          as_json=args.json)
    print(text, file=sys.stderr if rc == 2 else sys.stdout)
    return rc


def cmd_engine(args: argparse.Namespace) -> int:
    """rt engine stats/ticks/requests: the ContinuousEngine flight-
    recorder plane (util/engine_recorder.py). Each live engine's drain
    thread pushes an @engine/ KV snapshot (summary + tick/request record
    tails); this reads them straight off the GCS — no driver attach, so
    it works while the engine is saturated."""
    gcs = _resolve_gcs(args.address)
    if gcs is None:
        print("rt engine: no running cluster found (pass --address)",
              file=sys.stderr)
        return 1
    try:
        keys = _gcs_call(gcs, "kv_keys",
                         {"prefix": "@engine/"}).get("keys") or []
        snaps = []
        for k in sorted(keys):
            raw = _gcs_call(gcs, "kv_get", {"key": k}).get("value")
            if not raw:
                continue
            try:
                snaps.append(json.loads(raw))
            except ValueError:
                continue
    except Exception as e:  # noqa: BLE001 — one line, no stack trace
        print(f"rt engine: cannot reach GCS at {gcs}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 1
    if args.name:
        snaps = [s for s in snaps
                 if args.name in f"{s.get('node')}:{s.get('name')}"]
    if args.json:
        if args.engine_cmd == "stats":
            print(json.dumps(snaps, indent=2, default=str))
        else:
            key = "ticks" if args.engine_cmd == "ticks" else "requests"
            print(json.dumps(
                [{"engine": f"{s.get('node')}:{s.get('name')}",
                  key: (s.get(key) or [])[-args.limit:]} for s in snaps],
                indent=2, default=str))
        return 0
    if not snaps:
        print("(no engine flight-recorder snapshots — no live "
              "ContinuousEngine, or RT_ENGINE_RECORDER=0)")
        return 0
    for s in snaps:
        label = f"{s.get('node')}:{s.get('pid')}:{s.get('name')}"
        summ = s.get("summary") or {}
        if args.engine_cmd == "stats":
            print(f"engine {label}")
            print(f"  ticks {summ.get('ticks_total', 0)}  active "
                  f"{summ.get('active', 0)}  requests "
                  f"{summ.get('requests_total', 0)} "
                  f"({summ.get('cancelled_total', 0)} cancelled)  swaps "
                  f"{summ.get('swaps', 0)}")
            phases = summ.get("phase_s") or {}
            if phases:
                total = sum(phases.values()) or 1.0
                parts = "  ".join(f"{p}={1e3 * v:.1f}ms"
                                  f"({100 * v / total:.0f}%)"
                                  for p, v in phases.items())
                print(f"  phases [{summ.get('window_ticks', 0)} ticks, "
                      f"sum/wall {summ.get('phase_sum_ratio', 0):.2f}]: "
                      f"{parts}")
            print(f"  tick-gap p50 {1e3 * summ.get('tick_gap_p50_s', 0):.2f}"
                  f"ms  p99 {1e3 * summ.get('tick_gap_p99_s', 0):.2f}ms  "
                  f"max {1e3 * summ.get('tick_gap_max_s', 0):.2f}ms")
            if summ.get("window_completed"):
                print(f"  slo[{summ['window_completed']} reqs]: ttft "
                      f"{summ.get('ttft_attainment', 0):.2f} "
                      f"(p99 {1e3 * summ.get('ttft_p99_s', 0):.0f}ms vs "
                      f"{1e3 * summ.get('ttft_slo_s', 0):.0f}ms)  tpot "
                      f"{summ.get('tpot_attainment', 0):.2f} "
                      f"(p99 {1e3 * summ.get('tpot_p99_s', 0):.1f}ms vs "
                      f"{1e3 * summ.get('tpot_slo_s', 0):.1f}ms)")
                print(f"  goodput {summ.get('goodput_tok_s', 0):.1f} tok/s"
                      f" of {summ.get('window_tok_s', 0):.1f} tok/s "
                      f"(capacity est {summ.get('capacity_tok_s', 0):.1f})"
                      f"  decode-eff {summ.get('decode_efficiency', 0):.2f}"
                      f"  occupancy {summ.get('occupancy', 0):.2f}")
            print(f"  recorder overhead "
                  f"{100 * summ.get('overhead_frac', 0):.3f}% of tick wall")
        elif args.engine_cmd == "ticks":
            print(f"engine {label} — last {args.limit} tick(s)")
            for t in (s.get("ticks") or [])[-args.limit:]:
                when = time.strftime("%H:%M:%S",
                                     time.localtime(t.get("t", 0)))
                phases = "  ".join(f"{p}={v:.1f}"
                                   for p, v in (t.get("phases_ms")
                                                or {}).items())
                gap = (f"  gap={t['gap_ms']:.1f}ms"
                       if "gap_ms" in t else "")
                print(f"  {when} #{t.get('seq'):<6} "
                      f"wall={t.get('wall_ms', 0):.1f}ms "
                      f"active={t.get('active')}/{t.get('bucket')} "
                      f"k={t.get('k')} tok={t.get('tokens')}{gap}  "
                      f"[{phases}]")
        else:  # requests
            print(f"engine {label} — last {args.limit} request(s)")
            for r in (s.get("requests") or [])[-args.limit:]:
                rid_note = (f" rid={r['request_id'][:8]}"
                            if r.get("request_id") else "")
                print(f"  #{r.get('rid'):<5} {r.get('state'):<9} "
                      f"queue={r.get('queue_wait_ms', 0):.1f}ms "
                      f"prompt={r.get('prompt_tokens')} "
                      f"(cached {r.get('cached_tokens')}) "
                      f"tok={r.get('tokens')} "
                      f"ticks={r.get('decode_ticks')} "
                      f"ttft={r.get('ttft_ms', 0):.1f}ms "
                      f"tpot={r.get('tpot_ms', 0):.2f}ms{rid_note}")
    return 0


def cmd_rlhf(args: argparse.Namespace) -> int:
    """rt rlhf stats: the RLHF pipeline flight-recorder plane
    (util/pipeline_recorder.py). The driver's drain thread pushes an
    @rlhf/ KV snapshot (bubble/staleness/transfer rollup + iteration
    record tail); this reads it straight off the GCS — so it works
    POSTMORTEM, after the pipeline driver exited. A missing snapshot is
    an ERROR here (exit 1), unlike `rt engine stats`: you run this to
    grade a pipeline, and grading nothing is a mistake worth failing."""
    gcs = _resolve_gcs(args.address)
    if gcs is None:
        print("rt rlhf: no running cluster found (pass --address)",
              file=sys.stderr)
        return 1
    try:
        keys = _gcs_call(gcs, "kv_keys",
                         {"prefix": "@rlhf/"}).get("keys") or []
        snaps = []
        for k in sorted(keys):
            raw = _gcs_call(gcs, "kv_get", {"key": k}).get("value")
            if not raw:
                continue
            try:
                snaps.append(json.loads(raw))
            except ValueError:
                continue
    except Exception as e:  # noqa: BLE001 — one line, no stack trace
        print(f"rt rlhf: cannot reach GCS at {gcs}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 1
    if args.name:
        snaps = [s for s in snaps
                 if args.name in f"{s.get('node')}:{s.get('name')}"]
    if not snaps:
        what = (f"matching {args.name!r} " if args.name else "")
        print(f"rt rlhf: no pipeline flight-recorder snapshot {what}"
              f"under @rlhf/ (pipeline never ran, recorder closed, or "
              f"RT_RLHF_RECORDER=0)", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(snaps, indent=2, default=str))
        return 0
    now = time.time()
    for s in snaps:
        label = f"{s.get('node')}:{s.get('pid')}:{s.get('name')}"
        summ = s.get("summary") or {}
        age = max(0.0, now - (s.get("t") or now))
        print(f"rlhf {label}  (snapshot {age:.0f}s old)")
        stale = summ.get("staleness") or {}
        print(f"  iterations {summ.get('iterations_total', 0)} "
              f"({summ.get('interrupted_total', 0)} interrupted)  "
              f"tokens {summ.get('tokens', 0)}  bubble "
              f"{summ.get('bubble_fraction', 0):.3f} (last "
              f"{summ.get('bubble_last', 0):.3f})  coverage "
              f"{summ.get('coverage', 0):.3f}  staleness last "
              f"{stale.get('last', 0)} p99 {stale.get('p99', 0)} "
              f"max {stale.get('max', 0)}")
        busy = summ.get("role_busy_frac") or {}
        if busy:
            parts = "  ".join(f"{r}={100 * v:.0f}%"
                              for r, v in busy.items())
            print(f"  role busy share of pipeline span: {parts}")
        actor = summ.get("actor_s") or {}
        driver = summ.get("driver_s") or {}
        tax = summ.get("tax_s") or {}
        if driver:
            parts = "  ".join(
                f"{p}={1e3 * driver.get(p, 0):.0f}ms"
                f"(tax {1e3 * tax.get(p, 0):.0f}ms)" for p in driver)
            print(f"  driver phases (orchestration tax): {parts}")
        if actor:
            parts = "  ".join(f"{p}={1e3 * v:.0f}ms"
                              for p, v in actor.items())
            print(f"  actor phases: {parts}")
        rcpt = summ.get("receipt_last") or {}
        if rcpt:
            print(f"  transfer[v{rcpt.get('version', 0)} "
                  f"{rcpt.get('transport', '?')}]: "
                  f"{rcpt.get('nbytes', 0) / 1e6:.2f}MB "
                  f"{rcpt.get('n_leaves', 0)} leaves "
                  f"({rcpt.get('oid_leaves', 0)} oid / "
                  f"{rcpt.get('inline_leaves', 0)} inline)  pump "
                  f"{1e3 * rcpt.get('pump_wall_s', 0):.1f}ms  fetch "
                  f"{1e3 * rcpt.get('fetch_wall_s', 0):.1f}ms  barrier "
                  f"{1e3 * rcpt.get('barrier_drain_s', 0):.1f}ms  swap "
                  f"{1e3 * rcpt.get('swap_apply_s', 0):.2f}ms")
        intr = summ.get("interrupted_last")
        if intr:
            when = time.strftime("%H:%M:%S",
                                 time.localtime(intr.get("t", 0)))
            gaps = summ.get("restart_gaps_s") or []
            gap_note = (f"  restart gap {gaps[-1]:.2f}s"
                        if gaps else "")
            print(f"  last interrupt: {intr.get('phase')} @ {when} "
                  f"({intr.get('error', '')[:60]}){gap_note}")
        print(f"  recorder overhead "
              f"{100 * summ.get('overhead_frac', 0):.3f}% of iteration "
              f"wall")
        for r in (s.get("iterations") or [])[-args.limit:]:
            when = time.strftime("%H:%M:%S",
                                 time.localtime(r.get("t", 0)))
            if r.get("state") == "interrupted":
                print(f"  {when} #{r.get('seq'):<4} INTERRUPTED in "
                      f"{r.get('phase')} ({r.get('error', '')[:50]})")
                continue
            gap = (f" gap={r['restart_gap_s']:.2f}s"
                   if "restart_gap_s" in r else "")
            print(f"  {when} #{r.get('seq'):<4} iter "
                  f"{r.get('iteration')} wall={r.get('wall_ms', 0):.0f}"
                  f"ms bubble={r.get('bubble_fraction', 0):.3f} "
                  f"cov={r.get('coverage', 0):.2f} "
                  f"stale={r.get('staleness', 0)}{gap}")
    return 0


def cmd_train(args: argparse.Namespace) -> int:
    """rt train stats: the StepDriver flight-recorder plane
    (util/train_recorder.py). The driver's drain thread pushes an
    @train/ KV snapshot (phase rollup, launch-gap accounting, the
    MFU-gap waterfall + launch record tail); this reads it straight off
    the GCS — so it works POSTMORTEM, after the training run finished
    (the @train/ key deliberately survives the recorder). A missing
    snapshot is an ERROR (exit 1), same discipline as `rt rlhf stats`:
    you run this to grade a training run, and grading nothing is a
    mistake worth failing."""
    gcs = _resolve_gcs(args.address)
    if gcs is None:
        print("rt train: no running cluster found (pass --address)",
              file=sys.stderr)
        return 1
    try:
        keys = _gcs_call(gcs, "kv_keys",
                         {"prefix": "@train/"}).get("keys") or []
        snaps = []
        for k in sorted(keys):
            raw = _gcs_call(gcs, "kv_get", {"key": k}).get("value")
            if not raw:
                continue
            try:
                snaps.append(json.loads(raw))
            except ValueError:
                continue
    except Exception as e:  # noqa: BLE001 — one line, no stack trace
        print(f"rt train: cannot reach GCS at {gcs}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 1
    if args.name:
        snaps = [s for s in snaps
                 if args.name in f"{s.get('node')}:{s.get('name')}"]
    if not snaps:
        what = (f"matching {args.name!r} " if args.name else "")
        print(f"rt train: no train flight-recorder snapshot {what}"
              f"under @train/ (no fused launch ran, or "
              f"RT_TRAIN_RECORDER=0)", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(snaps, indent=2, default=str))
        return 0
    now = time.time()
    for s in snaps:
        label = f"{s.get('node')}:{s.get('pid')}:{s.get('name')}"
        summ = s.get("summary") or {}
        age = max(0.0, now - (s.get("t") or now))
        print(f"train {label}  (snapshot {age:.0f}s old)")
        print(f"  launches {summ.get('launches_total', 0)} "
              f"({summ.get('compiles', 0)} compiled)  steps "
              f"{summ.get('steps_total', 0)}  tokens "
              f"{summ.get('tokens', 0)}  "
              f"{summ.get('tokens_per_s', 0):.0f} tok/s  phase coverage "
              f"{summ.get('phase_sum_ratio', 0):.3f} of launch wall")
        phases = summ.get("phase_s") or {}
        if phases:
            parts = "  ".join(f"{p}={1e3 * v:.1f}ms"
                              for p, v in phases.items())
            print(f"  phases (window sums): {parts}")
        gp50 = 1e3 * summ.get("launch_gap_p50_s", 0)
        gp99 = 1e3 * summ.get("launch_gap_p99_s", 0)
        gmax = 1e3 * summ.get("launch_gap_max_s", 0)
        print(f"  launch gap p50={gp50:.1f}ms p99={gp99:.1f}ms "
              f"max={gmax:.1f}ms  dry-resets {summ.get('dry_resets', 0)}"
              f"  data_wait {100 * summ.get('data_wait_frac', 0):.1f}% "
              f"of wall")
        wf = summ.get("waterfall") or {}
        if wf:
            print(f"  MFU waterfall: raw {wf.get('raw_mfu', 0):.4f} -> "
                  f"achieved {wf.get('achieved_mfu', 0):.4f}  (gap "
                  f"{100 * summ.get('mfu_gap_frac', 0):.1f}%, marginal "
                  f"{summ.get('marginal_mfu', 0):.4f})")
            cost = wf.get("mfu_cost") or {}
            parts = "  ".join(f"{b}={v:.4f}"
                              for b, v in cost.items() if v > 0)
            if parts:
                print(f"  gap attribution (MFU cost): {parts}")
        print(f"  recorder overhead "
              f"{100 * summ.get('overhead_frac', 0):.3f}% of launch wall")
        for r in (s.get("launches") or [])[-args.limit:]:
            when = time.strftime("%H:%M:%S",
                                 time.localtime(r.get("t", 0)))
            pm = r.get("phases_ms") or {}
            parts = " ".join(f"{p}={v:.1f}" for p, v in pm.items())
            gap = (f" gap={r['gap_ms']:.1f}ms" if "gap_ms" in r else "")
            done = "" if r.get("done") else "  IN-FLIGHT"
            print(f"  {when} #{r.get('seq'):<4} k={r.get('k')} "
                  f"wall={r.get('wall_ms', 0):.1f}ms [{parts}]"
                  f"{gap}{done}")
    return 0


def cmd_metrics(args: argparse.Namespace) -> int:
    from ray_tpu.util.metrics import metrics_text

    rt = _attach_driver(args.address)
    try:
        print(metrics_text(), end="")
        return 0
    finally:
        rt.shutdown()


def cmd_export_grafana(args: argparse.Namespace) -> int:
    """rt metrics-export-grafana: turnkey Grafana/Prometheus provisioning
    (reference: ``dashboard/modules/metrics/grafana_dashboard_factory``)."""
    from ray_tpu.dashboard.grafana import export_grafana, \
        snapshot_user_metrics

    user = []
    if args.address:
        rt = _attach_driver(args.address)
        try:
            user = snapshot_user_metrics()
        finally:
            rt.shutdown()
    paths = export_grafana(args.out, prom_url=args.prom_url,
                           metrics_target=args.metrics_target,
                           user_metrics=user)
    for k, v in paths.items():
        print(f"{k}: {v}")
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv[:1] == ["profile"]:
        # passthrough: one parser (scripts/profile.py), one source of
        # truth — `rt profile --help` shows its full flag set
        from ray_tpu.scripts import profile as _profile

        return _profile.main(argv[1:])
    if argv[:1] == ["lint"]:
        # passthrough like profile: analysis/runner.py owns the flag set
        # (`rt lint [--json] [--baseline-update] [paths...]`)
        from ray_tpu.analysis import runner as _lint

        return _lint.main(argv[1:])
    parser = argparse.ArgumentParser(prog="rt")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_start = sub.add_parser("start", help="start a head or worker node")
    p_start.add_argument("--head", action="store_true")
    p_start.add_argument("--address", default=None)
    p_start.add_argument("--host", default="127.0.0.1")
    p_start.add_argument("--port", type=int, default=0)
    p_start.add_argument("--num-cpus", type=float, default=None)
    p_start.add_argument("--num-tpus", type=float, default=None)
    p_start.add_argument("--resources", default=None)
    p_start.add_argument("--session-name", default=None)
    p_start.add_argument("--timeout", type=float, default=30.0)
    p_start.set_defaults(fn=cmd_start)

    p_stop = sub.add_parser("stop", help="stop all nodes on this machine")
    p_stop.add_argument("--force", action="store_true")
    p_stop.add_argument("--timeout", type=float, default=10.0)
    p_stop.set_defaults(fn=cmd_stop)

    p_status = sub.add_parser("status", help="show cluster nodes")
    p_status.add_argument("--address", default=None)
    p_status.set_defaults(fn=cmd_status)

    p_job = sub.add_parser("job", help="submit / inspect jobs")
    job_sub = p_job.add_subparsers(dest="job_cmd", required=True)
    pj_submit = job_sub.add_parser("submit")
    pj_submit.add_argument("--address", default=None)
    pj_submit.add_argument("--env", action="append", metavar="K=V")
    pj_submit.add_argument("--wait", action="store_true",
                           help="stream logs until the job finishes")
    pj_submit.add_argument("entrypoint", nargs=argparse.REMAINDER)
    for name in ("status", "logs", "stop"):
        pj = job_sub.add_parser(name)
        pj.add_argument("--address", default=None)
        pj.add_argument("job_id")
        if name == "logs":
            pj.add_argument("--follow", action="store_true")
    pj_list = job_sub.add_parser("list")
    pj_list.add_argument("--address", default=None)
    p_job.set_defaults(fn=cmd_job)

    p_list = sub.add_parser("list", help="state API listings")
    p_list.add_argument("what", choices=sorted(_LIST_RPCS) + ["jobs"])
    p_list.add_argument("--address", default=None)
    p_list.add_argument("--limit", type=int, default=200)
    p_list.set_defaults(fn=cmd_list)

    # `rt profile` is routed in main() before parsing (scripts/profile.py
    # owns the flag set); this stub only makes it show up in `rt --help`
    sub.add_parser(
        "profile", add_help=False,
        help="step profiler: per-step wall/compile/sync breakdown + MFU "
             "over a model preset (util/step_profiler.py)")

    # `rt lint` is routed in main() before parsing too (analysis/runner.py
    # owns the flag set); stub for `rt --help` discoverability
    sub.add_parser(
        "lint", add_help=False,
        help="concurrency/runtime-invariant static analysis with a "
             "ratcheted baseline (ray_tpu/analysis)")

    p_micro = sub.add_parser("microbenchmark",
                             help="core-ops throughput sweep")
    p_micro.set_defaults(fn=lambda a: __import__(
        "ray_tpu.scripts.microbenchmark",
        fromlist=["main"]).main(a))

    p_scale = sub.add_parser(
        "scale-envelope",
        help="one-host scalability envelope (reference: "
             "release/benchmarks/README.md)")
    p_scale.add_argument("--actors", type=int, default=1000)
    p_scale.add_argument("--queued", type=int, default=10_000)
    p_scale.add_argument("--pgs", type=int, default=100)
    p_scale.add_argument("--actor-budget-s", type=float, default=120.0)
    p_scale.add_argument("--out", type=str, default="")
    p_scale.set_defaults(fn=lambda a: __import__(
        "ray_tpu.scripts.scale_envelope", fromlist=["main"]).main(
        ["--actors", str(a.actors), "--queued", str(a.queued),
         "--pgs", str(a.pgs), "--actor-budget-s", str(a.actor_budget_s)]
        + (["--out", a.out] if a.out else [])))

    p_serve = sub.add_parser("serve", help="deploy/inspect serve apps")
    serve_sub = p_serve.add_subparsers(dest="serve_cmd", required=True)
    ps_deploy = serve_sub.add_parser("deploy")
    ps_deploy.add_argument("config", help="YAML config (serve/schema.py)")
    ps_deploy.add_argument("--address", default=None)
    for name in ("status", "shutdown"):
        ps = serve_sub.add_parser(name)
        ps.add_argument("--address", default=None)
        if name == "status":
            ps.add_argument("-v", "--verbose", action="store_true",
                            help="include the autoscaler decision log")
            ps.add_argument("--json", action="store_true",
                            help="full detailed-status payload as JSON")
    p_serve.set_defaults(fn=cmd_serve)

    p_rl = sub.add_parser("rl", help="train / evaluate RL algorithms")
    rl_sub = p_rl.add_subparsers(dest="rl_cmd", required=True)
    pr_train = rl_sub.add_parser("train")
    pr_train.add_argument("--run", default=None,
                          help="algorithm name (PPO, DQN, SAC, ...)")
    pr_train.add_argument("-f", "--file", default=None,
                          help="tuned-example YAML (path or bundled name; "
                               "see `rt rl examples`)")
    pr_train.add_argument("--env", default=None)
    pr_train.add_argument("--config", default=None,
                          help="JSON dict of AlgorithmConfig overrides")
    pr_train.add_argument("--config-file", default=None,
                          help="YAML/JSON file of config overrides")
    pr_train.add_argument("--stop-iters", type=int, default=None,
                          help="iteration cap (default 10; with -f, the "
                               "YAML's stop block)")
    pr_train.add_argument("--stop-reward", type=float, default=None)
    pr_train.add_argument("--stop-timesteps", type=int, default=None)
    pr_train.add_argument("--checkpoint-dir", default=None)
    pr_train.add_argument("--address", default=None)
    pr_eval = rl_sub.add_parser("evaluate")
    pr_eval.add_argument("checkpoint", help="checkpoint dir from train")
    pr_eval.add_argument("--run", default=None)
    pr_eval.add_argument("--episodes", type=int, default=10)
    pr_eval.add_argument("--address", default=None)
    pr_rlhf = rl_sub.add_parser(
        "rlhf", help="run the end-to-end RLHF pipeline (placed roles, "
                     "continuous-engine generation, streamed weight sync)")
    pr_rlhf.add_argument("--address", default=None)
    pr_rlhf.add_argument("--preset", default="debug",
                         help="llama preset for all roles (default debug)")
    pr_rlhf.add_argument("--iters", type=int, default=2)
    pr_rlhf.add_argument("--prompts", type=int, default=4,
                         help="sequences per iteration")
    pr_rlhf.add_argument("--prompt-len", type=int, default=8)
    pr_rlhf.add_argument("--max-new", type=int, default=16)
    pr_rlhf.add_argument("--slots", type=int, default=4,
                         help="generation engine decode slots")
    pr_rlhf.add_argument("--seed", type=int, default=0)

    pr_ex = rl_sub.add_parser("examples",
                              help="list bundled tuned examples")
    pr_ex.add_argument("--address", default=None)
    p_rl.set_defaults(fn=cmd_rl)

    p_graf = sub.add_parser(
        "metrics-export-grafana",
        help="write Grafana dashboards + provisioning + prometheus.yml")
    p_graf.add_argument("--out", required=True)
    p_graf.add_argument("--prom-url", default="http://127.0.0.1:9090")
    p_graf.add_argument("--metrics-target", default="127.0.0.1:8265")
    p_graf.add_argument("--address", default=None,
                        help="live cluster to harvest user metrics from")
    p_graf.set_defaults(fn=cmd_export_grafana)

    p_metrics = sub.add_parser("metrics",
                               help="aggregated Prometheus metrics page")
    p_metrics.add_argument("--address", default=None)
    p_metrics.set_defaults(fn=cmd_metrics)

    p_mem = sub.add_parser(
        "memory",
        help="memory plane: per-node store usage, per-object owner table, "
             "leak suspects (util/memory.py; `ray memory` analog)")
    p_mem.add_argument("--address", default=None)
    p_mem.add_argument("--oom", action="store_true",
                       help="replay recent OOM-kill post-mortems")
    p_mem.add_argument("--device", action="store_true",
                       help="include the per-device HBM table")
    p_mem.add_argument("--limit", type=int, default=200,
                       help="per-owner / per-node object rows")
    p_mem.add_argument("--top", type=int, default=10,
                       help="rows in the largest-objects view")
    p_mem.add_argument("--leak-age", type=float, default=None,
                       help="leak-suspect age threshold seconds "
                            "(default RT_MEMORY_LEAK_AGE_S)")
    p_mem.add_argument("id", nargs="?", default=None,
                       help="with --oom: filter post-mortems by victim "
                            "worker id, object id, or node id prefix")
    p_mem.set_defaults(fn=cmd_memory)

    p_err = sub.add_parser(
        "errors",
        help="tail the categorized FailureEvent feed (death-cause "
             "taxonomy; GCS failure_events store)")
    p_err.add_argument("--address", default=None)
    p_err.add_argument("--category", default=None,
                       help="only this death-cause category "
                            "(e.g. worker_crash, oom_kill, task_error)")
    p_err.add_argument("--limit", type=int, default=200)
    p_err.add_argument("--json", action="store_true")
    p_err.add_argument("--origin", default=None,
                       choices=("chaos", "organic", "recovery"),
                       help="only chaos-injected, recovery-plane, or "
                            "organic failures")
    p_err.set_defaults(fn=cmd_errors)

    p_sched = sub.add_parser(
        "sched",
        help="placement receipts: scheduling decision records and the "
             "cross-node balance snapshot (GCS placement_events store)")
    sched_sub = p_sched.add_subparsers(dest="sched_cmd", required=True)
    ps_dec = sched_sub.add_parser(
        "decisions", help="tail the placement decision feed")
    ps_dec.add_argument("--address", default=None)
    ps_dec.add_argument("--kind", default=None,
                        help="only this decision kind (dispatch_local, "
                             "spillback, actor_place, pg_place, "
                             "warm_adopt, gang_place)")
    ps_dec.add_argument("--node", default=None,
                        help="only decisions whose chosen or origin node "
                             "id starts with this prefix")
    ps_dec.add_argument("--limit", type=int, default=200)
    ps_dec.add_argument("--json", action="store_true")
    ps_bal = sched_sub.add_parser(
        "balance", help="per-node queued+running load + imbalance CoV")
    ps_bal.add_argument("--address", default=None)
    ps_bal.add_argument("--json", action="store_true")
    p_sched.set_defaults(fn=cmd_sched)

    p_chaos = sub.add_parser(
        "chaos",
        help="fault injection: arm/disarm a seeded ChaosPlan against the "
             "live cluster (util/chaos.py)")
    chaos_sub = p_chaos.add_subparsers(dest="chaos_cmd", required=True)
    pc_arm = chaos_sub.add_parser("arm")
    pc_arm.add_argument("--address", default=None)
    pc_arm.add_argument("--plan", default=None,
                        help="JSON plan file ({seed, faults: [...]})")
    pc_arm.add_argument("--site", default=None,
                        help="single-fault shorthand: injection site name "
                             "(worker.kill, raylet.kill_worker, rpc.drop, "
                             "object.lose, oom.pressure, ...)")
    pc_arm.add_argument("--at", type=int, default=None,
                        help="fire exactly on the Nth hit of the site")
    pc_arm.add_argument("--after", type=int, default=None,
                        help="fire on every hit after the Nth")
    pc_arm.add_argument("--prob", type=float, default=None,
                        help="fire with this (seeded) probability")
    pc_arm.add_argument("--max-fires", type=int, default=None,
                        dest="max_fires")
    pc_arm.add_argument("--delay", type=float, default=None,
                        help="delay_s for rpc.delay / spill.slow")
    pc_arm.add_argument("--value", type=float, default=None,
                        help="effect value (oom.pressure fraction)")
    pc_arm.add_argument("--target", default=None,
                        help="substring match on the site's target "
                             "(fn/method/rpc name, object id)")
    pc_arm.add_argument("--seed", type=int, default=0)
    for name in ("disarm", "status"):
        pc = chaos_sub.add_parser(name)
        pc.add_argument("--address", default=None)
    p_chaos.set_defaults(fn=cmd_chaos)

    p_doc = sub.add_parser(
        "doctor",
        help="one-shot cluster health report; exit 0 healthy / 1 "
             "unhealthy / 2 unreachable (util/doctor.py)")
    p_doc.add_argument("--address", default=None)
    p_doc.add_argument("--window", type=float, default=600.0,
                       help="recency window (s) for failure/OOM findings")
    p_doc.add_argument("--queue-warn", type=int, default=100,
                       help="raylet queue depth that warrants a warning")
    p_doc.add_argument("--queue-wait-warn", type=float, default=10.0,
                       help="per-scheduling-class queue-wait p99 (s) that "
                            "grades the class as starving")
    p_doc.add_argument("--serve-p99-warn", type=float, default=5.0,
                       help="serve request p99 (s) that grades a "
                            "deployment as degraded")
    p_doc.add_argument("--imbalance-warn", type=float, default=0.5,
                       help="cross-node load CoV that, sustained over 3 "
                            "ticks, grades the cluster as imbalanced")
    p_doc.add_argument("--tick-gap-warn", type=float, default=0.5,
                       help="engine decode tick-gap (s) that, sustained "
                            "over 3 launches, grades decode as starved")
    p_doc.add_argument("--slo-warn", type=float, default=0.9,
                       help="engine TTFT/TPOT SLO-attainment ratio below "
                            "which a loaded engine is graded degraded")
    p_doc.add_argument("--bubble-warn", type=float, default=0.75,
                       help="RLHF pipeline bubble fraction that, "
                            "sustained over 3 iterations, grades the "
                            "dataflow as phase-serialized waste")
    p_doc.add_argument("--launch-gap-warn", type=float, default=0.25,
                       help="train launch-gap (s) that, sustained over 3 "
                            "launches with a stacked batch available, "
                            "grades the devices as host-starved")
    p_doc.add_argument("--data-wait-warn", type=float, default=0.25,
                       help="train data_wait share of window wall above "
                            "which the driver is graded data-starved")
    p_doc.add_argument("--json", action="store_true")
    p_doc.set_defaults(fn=cmd_doctor)

    p_eng = sub.add_parser(
        "engine",
        help="ContinuousEngine flight recorder: tick phase attribution, "
             "request lifecycles, SLO/goodput rollup (@engine/ KV "
             "snapshots, util/engine_recorder.py)")
    eng_sub = p_eng.add_subparsers(dest="engine_cmd", required=True)
    for name, what in (("stats", "per-engine SLO/goodput/phase rollup"),
                       ("ticks", "tail the per-tick phase records"),
                       ("requests", "tail the request lifecycle records")):
        pe = eng_sub.add_parser(name, help=what)
        pe.add_argument("--address", default=None)
        pe.add_argument("--name", default=None,
                        help="only engines whose node:name contains this")
        pe.add_argument("--limit", type=int, default=20)
        pe.add_argument("--json", action="store_true")
    p_eng.set_defaults(fn=cmd_engine)

    p_rlhf_top = sub.add_parser(
        "rlhf",
        help="RLHF pipeline flight recorder: per-role bubble "
             "attribution, orchestration tax, staleness and transfer "
             "receipts (@rlhf/ KV snapshots, util/pipeline_recorder.py)")
    rlhf_sub = p_rlhf_top.add_subparsers(dest="rlhf_cmd", required=True)
    pr_stats = rlhf_sub.add_parser(
        "stats", help="per-pipeline bubble/staleness/transfer rollup "
                      "(works postmortem — reads the GCS snapshot)")
    pr_stats.add_argument("--address", default=None)
    pr_stats.add_argument("--name", default=None,
                          help="only pipelines whose node:name contains "
                               "this")
    pr_stats.add_argument("--limit", type=int, default=8,
                          help="iteration-record tail to render")
    pr_stats.add_argument("--json", action="store_true")
    p_rlhf_top.set_defaults(fn=cmd_rlhf)

    p_train_top = sub.add_parser(
        "train",
        help="StepDriver flight recorder: per-launch phase attribution, "
             "launch-gap/data-starvation accounting, MFU-gap waterfall "
             "(@train/ KV snapshots, util/train_recorder.py)")
    train_sub = p_train_top.add_subparsers(dest="train_cmd", required=True)
    pt_stats = train_sub.add_parser(
        "stats", help="per-driver phase/gap/MFU-waterfall rollup (works "
                      "postmortem — the @train/ snapshot survives the "
                      "run)")
    pt_stats.add_argument("--address", default=None)
    pt_stats.add_argument("--name", default=None,
                          help="only drivers whose node:name contains "
                               "this")
    pt_stats.add_argument("--limit", type=int, default=8,
                          help="launch-record tail to render")
    pt_stats.add_argument("--json", action="store_true")
    p_train_top.set_defaults(fn=cmd_train)

    p_trace = sub.add_parser(
        "trace",
        help="span tree + per-phase latency tables for a task or trace "
             "(util/tracing.py phase records)")
    p_trace.add_argument("id", help="task_id (prefix ok), trace_id, "
                                    "or span_id")
    p_trace.add_argument("--address", default=None)
    p_trace.add_argument("--limit", type=int, default=10000)
    p_trace.set_defaults(fn=cmd_trace)

    args = parser.parse_args(argv)
    if args.cmd == "start" and not args.head and not args.address:
        parser.error("rt start needs --head or --address=<gcs>")
    try:
        return args.fn(args)
    except BrokenPipeError:
        # downstream reader (grep -q, head) closed the pipe after it got
        # what it wanted — success, not failure; repoint stdout at
        # /dev/null so the interpreter's exit-time flush can't re-raise
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0


if __name__ == "__main__":
    sys.exit(main())
