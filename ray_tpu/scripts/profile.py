"""``rt profile`` — run N steps of a preset under the step profiler.

The command VERDICT's "profile, not a guess" directive asks for: spin up a
runtime, run a few train/generate/speculative/stream steps of a model
preset with ``util/step_profiler.py`` enabled, print the per-step breakdown
table (wall / compile / dispatch / device-sync, tokens/s, analytic MFU),
drain the records into the GCS event store, and optionally write the
Perfetto timeline (step/compile/sync lanes alongside the task lanes) so an
on-chip round can commit the artifact.

  rt profile --preset debug --mode train --steps 5 --batch 4 --seq 128
  rt profile --preset 160m --mode generate --new-tokens 32 --out trace.json
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
from typing import List, Optional


def _find_preset(name: str):
    """Look the preset up across the model families (names are disjoint:
    llama 'debug'/'160m'/..., moe 'moe-debug'/'8x160m'/...)."""
    from ray_tpu.models import llama, moe

    for presets in (llama.PRESETS, moe.PRESETS):
        if name in presets:
            return presets[name]
    known = sorted(list(llama.PRESETS) + list(moe.PRESETS))
    raise SystemExit(f"rt profile: unknown preset {name!r}; one of {known}")


def _fmt_table(records) -> str:
    head = (f"{'kind':<12} {'step':>4} {'wall ms':>9} {'compile ms':>11} "
            f"{'dispatch ms':>12} {'sync ms':>9} {'launches':>8} "
            f"{'st/ln':>6} {'tokens':>7} {'tok/s':>10} {'MFU':>7} "
            f"{'peak HBM MB':>12}")
    lines = [head, "-" * len(head)]
    for r in records:
        hbm = getattr(r, "hbm_peak_bytes", 0)
        hbm_col = f"{hbm / 1e6:>12.1f}" if hbm else f"{'-':>12}"
        spl = getattr(r, "steps", 1) / max(1, r.launches)
        lines.append(
            f"{r.kind:<12} {r.step:>4} {r.wall_s * 1e3:>9.2f} "
            f"{r.compile_s * 1e3:>11.2f} {r.dispatch_s * 1e3:>12.2f} "
            f"{r.execute_s * 1e3:>9.2f} {r.launches:>8} {spl:>6.1f} "
            f"{r.tokens:>7} {r.tokens_per_s:>10.1f} {r.mfu:>7.4f} {hbm_col}")
    return "\n".join(lines)


def _run_train(cfg, steps: int, batch: int, seq: int,
               steps_per_launch: int = 1) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.parallel import train_step as ts

    fam = ts.model_family(cfg)
    rng = jax.random.key(0)
    params = fam.init_params(rng, cfg)
    optimizer = ts.default_optimizer(total_steps=max(steps, 101))
    opt_state = jax.jit(optimizer.init)(params)
    if steps_per_launch > 1:
        # the product fast path: K steps fused per launch via StepDriver
        from ray_tpu.train.driver import StepDriver

        driver = StepDriver(cfg, optimizer,
                            steps_per_launch=steps_per_launch)
        rngs = jax.random.split(jax.random.key(1), steps)
        batches = ({"tokens": np.asarray(jax.random.randint(
            r, (batch, seq + 1), 0, cfg.vocab_size, jnp.int32))}
            for r in rngs)
        driver.run(params, opt_state, batches)
        return
    step = ts.make_train_step(cfg, optimizer)
    tokens = jax.random.randint(jax.random.key(1), (batch, seq + 1),
                                0, cfg.vocab_size, jnp.int32)
    data = {"tokens": tokens}
    for _ in range(steps):
        params, opt_state, _ = step(params, opt_state, data)


def _run_generate(cfg, steps: int, batch: int, seq: int, new_tokens: int,
                  mode: str) -> None:
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import generate as G
    from ray_tpu.parallel import train_step as ts

    fam = ts.model_family(cfg)
    params = fam.init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (batch, seq),
                                0, cfg.vocab_size, jnp.int32)
    if mode == "speculative":
        # draft = same family/vocab, half the layers — the CPU-smoke stand-in
        # for a real small draft checkpoint
        draft_cfg = dataclasses.replace(
            cfg, n_layers=max(1, cfg.n_layers // 2))
        draft_params = fam.init_params(jax.random.key(2), draft_cfg)
        for _ in range(steps):
            G.generate_speculative(params, draft_params, prompt, cfg,
                                   draft_cfg, max_new_tokens=new_tokens)
    elif mode == "stream":
        for _ in range(steps):
            for _tok in G.generate_stream(params, prompt, cfg,
                                          max_new_tokens=new_tokens):
                pass
    else:
        for _ in range(steps):
            G.generate(params, prompt, cfg, max_new_tokens=new_tokens)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(prog="rt profile")
    parser.add_argument("--preset", default="debug",
                        help="model preset (llama or moe families)")
    parser.add_argument("--mode", default="train",
                        choices=("train", "generate", "speculative",
                                 "stream"))
    parser.add_argument("--steps", type=int, default=5)
    parser.add_argument("--steps-per-launch", type=int, default=1,
                        help="train mode: fuse K optimizer steps into one "
                             "compiled launch (the product fast path)")
    parser.add_argument("--batch", type=int, default=4)
    parser.add_argument("--seq", type=int, default=128)
    parser.add_argument("--new-tokens", type=int, default=16)
    parser.add_argument("--out", default=None,
                        help="write the Perfetto trace JSON here")
    parser.add_argument("--json", default=None, metavar="FILE",
                        help="write a machine-readable profile artifact "
                             "(records + summary + MFU/steps-per-launch) "
                             "here; '-' for stdout")
    parser.add_argument("--jax-trace", default=None, metavar="DIR",
                        help="also capture a jax.profiler device trace "
                             "into DIR (best-effort; the real per-kernel "
                             "device timeline on TPU)")
    parser.add_argument("--address", default=None,
                        help="attach to a running cluster (default: own "
                             "single-node runtime)")
    parser.add_argument("--no-metrics", action="store_true",
                        help="skip the rt_step_* metrics section")
    args = parser.parse_args(argv)

    import ray_tpu
    from ray_tpu.util import step_profiler

    cfg = _find_preset(args.preset)

    owns = not ray_tpu.is_initialized()
    if owns:
        if args.address:
            ray_tpu.init(address=args.address)
        else:
            ray_tpu.init()
    step_profiler.enable()
    try:
        # one real task in the run so the exported timeline carries the
        # normal task lanes next to the step lanes
        @ray_tpu.remote
        def _platform_probe():
            import jax

            return {"backend": jax.default_backend(),
                    "devices": jax.local_device_count()}

        probe = ray_tpu.get(_platform_probe.remote(), timeout=120)

        tracing = False
        if args.jax_trace:
            import jax

            try:
                jax.profiler.start_trace(args.jax_trace)
                tracing = True
            except Exception as e:  # noqa: BLE001 — analytic path still runs
                print(f"jax.profiler trace unavailable: {e!r}",
                      file=sys.stderr)
        try:
            if args.mode == "train":
                _run_train(cfg, args.steps, args.batch, args.seq,
                           args.steps_per_launch)
            else:
                _run_generate(cfg, args.steps, args.batch, args.seq,
                              args.new_tokens, args.mode)
        finally:
            if tracing:
                import jax

                jax.profiler.stop_trace()
                print(f"jax.profiler device trace in {args.jax_trace}")

        records = step_profiler.records()
        drained = step_profiler.drain()
        print(f"# rt profile — preset={args.preset} mode={args.mode} "
              f"steps={args.steps} batch={args.batch} seq={args.seq} "
              f"platform={probe['backend']}x{probe['devices']}")
        print(_fmt_table(records))
        summ = step_profiler.summary()
        if summ:
            print(f"\nsteady-state: wall {summ['mean_wall_s'] * 1e3:.2f} ms"
                  f"/record, dispatch {summ['mean_dispatch_s'] * 1e3:.2f} ms, "
                  f"device sync {summ['mean_execute_s'] * 1e3:.2f} ms, "
                  f"compile total {summ['compile_s']:.2f} s, "
                  f"{summ['tokens_per_s']:.1f} tok/s, "
                  f"MFU {summ['mean_mfu']:.4f}")
            spl = summ.get("mean_steps_per_launch", 1.0)
            if spl > 1.0:
                # the launch-amortization line bench prints (run_sweep's
                # per_launch_overhead_s), reproduced from the profile so
                # the committed trace reads without the JSON
                print(f"launch amortization: {spl:.1f} steps/launch — "
                      f"per-launch dispatch "
                      f"{summ['mean_dispatch_s'] * 1e3:.2f} ms amortizes to "
                      f"{summ['mean_dispatch_s'] / spl * 1e3:.2f} ms/step; "
                      f"true per-step wall "
                      f"{summ['per_step_wall_s'] * 1e3:.2f} ms")
        print(f"drained {drained} step record(s) into the event store")

        if args.json:
            import json
            import time

            payload = {
                "schema": "rt-profile-v1",
                "t": time.time(),
                "config": {"preset": args.preset, "mode": args.mode,
                           "steps": args.steps, "batch": args.batch,
                           "seq": args.seq, "new_tokens": args.new_tokens,
                           "steps_per_launch": args.steps_per_launch},
                "platform": {"backend": probe["backend"],
                             "devices": probe["devices"]},
                "records": [r.to_dict() for r in records],
                "summary": summ or {},
            }
            if args.json == "-":
                print(json.dumps(payload, indent=2, sort_keys=True))
            else:
                with open(args.json, "w") as f:
                    json.dump(payload, f, indent=2, sort_keys=True)
                    f.write("\n")
                print(f"wrote {args.json}: {len(records)} record(s) + "
                      f"summary")
        if args.out:
            trace = ray_tpu.timeline(args.out)
            cats = sorted({t.get("cat") for t in trace})
            print(f"wrote {args.out}: {len(trace)} events, "
                  f"categories {cats}")
        if not args.no_metrics:
            from ray_tpu.util.metrics import flush_now, metrics_text

            flush_now()
            step_lines = [ln for ln in metrics_text().splitlines()
                          if "rt_step_" in ln]
            print("\n# rt_step_* metrics\n" + "\n".join(step_lines))
        return 0
    finally:
        step_profiler.disable()
        if owns:
            ray_tpu.shutdown()


if __name__ == "__main__":
    sys.exit(main())
