"""Cache-aware serving bench: shared-prefix trace, warm vs cold A/B.

The ROADMAP item-4 acceptance leg (ISSUE 15): a realistic shared-prefix
trace — 95% of requests share a long system prompt, each with a unique
user tail, plus multi-turn session replay — against the SAME engine
config with the prefix/KV cache on (warm) and off (cold control), at
equal offered load. Three legs:

  1. **engine TTFT A/B** — sequential requests straight into one
     ContinuousEngine, timed submit -> first token: the TTFT-collapse
     number with no serve-transport noise (warm prefill touches only
     the uncached suffix). Headline: ``ttft_collapse_x`` (>= 5x bar).
  2. **serve trace at equal load** — open-loop Poisson of the trace via
     deployment handles against warm and cold apps at the same rps:
     per-request TTFT percentiles + full-wall p99, hits advancing.
  3. **warm at 2x offered load** — the capacity claim: the warm app at
     DOUBLE the cold control's rps must hold p99 at or under the cold
     control's and shed no more (equal shed budget).

Session replay rides leg 2: a fraction of arrivals continue a session
(prompt = previous prompt + previous output + new user tokens), which
the capture-on-completion path keeps warm turn over turn.

Writes the committed artifact (default ``BENCH_KV_r10.json``); env
knobs: RT_KV_BENCH_{PREFIX,SUFFIX,NEW,RPS,SECS,SLOTS,OUT}.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional


def _engine_ttft_leg(preset: str, prefix_len: int, suffix_len: int,
                     max_new: int, slots: int, stride: int,
                     reqs: int = 24) -> Dict[str, Any]:
    """Leg 1: median submit->first-token wall, warm vs cold, one engine
    each (same compiled programs warmed outside the timed window)."""
    import jax
    import numpy as np

    from ray_tpu.models import llama
    from ray_tpu.models.serving import ContinuousEngine

    max_len = prefix_len + suffix_len + max_new + 8
    cfg = llama.PRESETS[preset]
    params = llama.init_params(jax.random.key(0), cfg)
    prefix = list(range(1, prefix_len + 1))
    rng = random.Random(3)

    def ttfts(engine, n: int, seed_cache: bool) -> List[float]:
        out: List[float] = []
        # one throwaway per distinct program (prefill shapes) + cache
        # seeding, outside the timed window
        for warmup in (True, False):
            rounds = 2 if warmup else n
            for i in range(rounds):
                tail = [200 + rng.randrange(1000) for _ in range(suffix_len)]
                ev = threading.Event()
                first_t = [0.0]

                def on_token(burst, ev=ev, first_t=first_t):
                    if not ev.is_set() and burst:
                        first_t[0] = time.perf_counter()
                        ev.set()

                t0 = time.perf_counter()
                h = engine.submit_cb(np.asarray(prefix + tail, np.int32),
                                     max_new, on_token)
                assert ev.wait(timeout=120)
                # drain to completion so the slot frees + pages capture
                while True:
                    st = engine.stats()
                    if st["active"] == 0 and st["pending"] == 0:
                        break
                    time.sleep(0.002)
                if not warmup:
                    out.append(first_t[0] - t0)
                del h
        return out

    res: Dict[str, Any] = {"requests": reqs, "prefix_tokens": prefix_len,
                           "suffix_tokens": suffix_len}
    for leg, kv_bytes in (("cold", 0), ("warm", 256 << 20)):
        engine = ContinuousEngine(params, cfg, max_slots=slots,
                                  max_len=max_len, decode_stride=stride,
                                  kv_cache_bytes=kv_bytes, kv_label=leg)
        vals = sorted(ttfts(engine, reqs, kv_bytes > 0))
        res[leg] = {
            "ttft_p50_ms": round(1e3 * vals[len(vals) // 2], 3),
            "ttft_mean_ms": round(1e3 * sum(vals) / len(vals), 3)}
        if kv_bytes > 0:
            st = engine.stats()["kv"]
            res[leg]["kv"] = {k: st[k] for k in
                              ("hits", "misses", "bytes", "pages",
                               "evictions")}
        engine.shutdown()
    res["ttft_collapse_x"] = round(
        res["cold"]["ttft_p50_ms"] / max(res["warm"]["ttft_p50_ms"], 1e-6),
        2)
    return res


class _Trace:
    """The shared-prefix request mix: 95% system-prompt + unique tail,
    5% unrelated cold prompts, plus multi-turn session continuations.
    Deterministic per seed so warm and cold legs see the same multiset."""

    def __init__(self, prefix: List[int], suffix_len: int, max_new: int,
                 seed: int, shared_frac: float = 0.95,
                 session_frac: float = 0.25, max_sessions: int = 8,
                 max_ctx: int = 0):
        self.prefix = prefix
        self.suffix_len = suffix_len
        self.max_new = max_new
        self.shared_frac = shared_frac
        self.session_frac = session_frac
        self.max_ctx = max_ctx
        self.rng = random.Random(seed)
        self.lock = threading.Lock()
        self.sessions: List[List[int]] = [list(prefix)
                                          for _ in range(max_sessions)]

    def next_body(self) -> Dict[str, Any]:
        with self.lock:
            r = self.rng.random()
            if r > self.shared_frac:
                # cold minority: unrelated prompt, no reuse possible
                toks = [5000 + self.rng.randrange(20000)
                        for _ in range(len(self.prefix) // 2)]
                return {"tokens": toks, "max_new_tokens": self.max_new}
            tail = [200 + self.rng.randrange(1000)
                    for _ in range(self.suffix_len)]
            if r < self.shared_frac * self.session_frac:
                # session replay: continue a growing context
                i = self.rng.randrange(len(self.sessions))
                ctx = self.sessions[i]
                if self.max_ctx and len(ctx) + self.suffix_len + \
                        self.max_new + 2 > self.max_ctx:
                    ctx = self.sessions[i] = list(self.prefix)
                prompt = ctx + tail
                return {"tokens": prompt, "max_new_tokens": self.max_new,
                        "_session": i}
            return {"tokens": self.prefix + tail,
                    "max_new_tokens": self.max_new}

    def record(self, body: Dict[str, Any], out: List[int]) -> None:
        i = body.get("_session")
        if i is None:
            return
        with self.lock:
            # next turn extends this turn's prompt + output
            self.sessions[i] = list(body["tokens"]) + list(out)


def _serve_leg(handle, trace: _Trace, rps: float, secs: float,
               seed: int) -> Dict[str, Any]:
    from ray_tpu.serve.llm import poisson_load

    def fire():
        body = dict(trace.next_body())
        sess = body.pop("_session", None)
        if sess is not None:
            body["_session"] = sess  # record() needs it; replica ignores
        send = {k: v for k, v in body.items() if not k.startswith("_")}
        t0 = time.perf_counter()
        gen = handle.remote(send).result()
        toks = []
        ttft: Optional[float] = None
        for t in gen:
            if ttft is None:
                ttft = time.perf_counter() - t0
            toks.append(t)
        trace.record(body, toks)
        return (len(toks), ttft if ttft is not None else 0.0)

    return poisson_load(fire, rps=rps, duration_s=secs, seed=seed)


def main(args=None) -> int:
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import continuous_llm_app

    preset = os.environ.get("RT_KV_BENCH_PRESET", "debug")
    # the realistic shared-prefix regime is a LONG system prompt (RAG /
    # agent preambles run 1-2k tokens) with short per-request tails:
    # prefill dominates per-request engine cost, which is exactly the
    # cost the cache removes — at short prefixes the shared decode
    # ceiling caps the warm leg's capacity gain instead
    prefix_len = int(os.environ.get("RT_KV_BENCH_PREFIX", "1024"))
    # suffix + max_new together make ONE chunk (64), so a session
    # context grows exactly chunk-aligned turn over turn: the restore
    # point c stays a small set of chunk multiples and the uncached
    # suffix keeps ONE shape — the (cached_len, suffix_len)-keyed
    # prefill program set stays bounded instead of compiling a fresh
    # XLA program per session depth (prompt-length bucketing, the
    # admission-cost discipline the engine docstring prescribes)
    suffix_len = int(os.environ.get("RT_KV_BENCH_SUFFIX", "56"))
    max_new = int(os.environ.get("RT_KV_BENCH_NEW", "8"))
    # leg 1 wants prefill compute the cache visibly removes: a longer
    # shared prefix than the serve legs need (its engines size max_len
    # independently)
    eng_prefix = int(os.environ.get("RT_KV_BENCH_ENG_PREFIX",
                                    str(max(512, prefix_len))))
    slots = int(os.environ.get("RT_KV_BENCH_SLOTS", "8"))
    stride = int(os.environ.get("RT_KV_BENCH_STRIDE", "4"))
    rps = float(os.environ.get("RT_KV_BENCH_RPS", "5"))
    secs = float(os.environ.get("RT_KV_BENCH_SECS", "12"))
    out_path = os.environ.get("RT_KV_BENCH_OUT", "BENCH_KV_r10.json")
    # session contexts grow turn over turn: size max_len for a couple
    # of turns (each extra depth is another (cached_len, suffix) prefill
    # program every leg must compile during its replay warmup)
    max_len = int(os.environ.get(
        "RT_KV_BENCH_MAX_LEN",
        str(prefix_len + 2 * (suffix_len + max_new) + 64)))

    started_here = False
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
        started_here = True

    artifact: Dict[str, Any] = {
        "schema": "rt-kv-bench-1", "preset": preset, "t": time.time(),
        "trace": {"shared_prefix_frac": 0.95, "session_frac": 0.25,
                  "prefix_tokens": prefix_len, "suffix_tokens": suffix_len,
                  "max_new_tokens": max_new},
        "note": ("warm = prefix/KV cache on, cold = kv_cache_bytes=0, "
                 "SAME engine/serve config and the same deterministic "
                 "trace; leg 1 is engine-level TTFT (no transport "
                 "noise), legs 2/3 are open-loop Poisson through serve "
                 "handles (ttft = first streamed token at the client)"),
    }
    try:
        # short fixed suffix for leg 1: the TTFT-collapse ceiling is the
        # cold-prefill compute the cache removes, so keep the uncached
        # tail minimal (the serve trace uses the chunk-sized tail)
        print(f"== leg 1: engine TTFT A/B ({eng_prefix}+4 tok prompts) ==")
        artifact["engine_ttft"] = _engine_ttft_leg(
            preset, eng_prefix, 4, max_new, slots, stride)
        e = artifact["engine_ttft"]
        print(f"cold p50 {e['cold']['ttft_p50_ms']}ms vs warm p50 "
              f"{e['warm']['ttft_p50_ms']}ms -> collapse x"
              f"{e['ttft_collapse_x']}")

        def fresh_trace():
            return _Trace(list(range(1, prefix_len + 1)), suffix_len,
                          max_new, seed=17, max_ctx=max_len - 8)

        # serve legs run as INTERLEAVED rounds, not one sequential pass
        # per leg: on a shared CPU box, ambient load drifts on a tens-of-
        # seconds scale — a sequential A/B hands one leg a quiet machine
        # and the other a noisy one (observed: the same leg's p99 moved
        # 181ms -> 691ms between back-to-back runs). Cycling
        # cold/warm/warm_2x in short slices and taking the MEDIAN across
        # rounds pins the comparison to the same ambient conditions.
        rounds = int(os.environ.get("RT_KV_BENCH_ROUNDS", "3"))
        handles = {}
        for leg, kv_bytes, leg_rps in (("cold", 0, rps),
                                       ("warm", 256 << 20, rps),
                                       ("warm_2x", 256 << 20, 2 * rps)):
            app = continuous_llm_app(
                preset, max_slots=slots, max_len=max_len,
                decode_stride=stride, name="KV",
                max_ongoing_requests=4 * slots, kv_cache_bytes=kv_bytes)
            name = f"kvb-{leg}"
            serve.run(app, name=name, route_prefix=f"/{name}")
            h = serve.get_deployment_handle("KV", name)
            # warmup: one boot request, then an UNTIMED replay of the
            # leg's full deterministic schedule (same seed -> same
            # prompt multiset, greedy decode -> same session turns).
            # Every prefill/restore shape the timed rounds will see is
            # compiled here, and the warm legs reach their steady-state
            # cache — a single mid-round XLA compile is a 1-2 s stall
            # that owns the p99 at these walls.
            list(h.remote({"tokens": list(range(1, prefix_len + 1)),
                           "max_new_tokens": 2}).result())
            _serve_leg(h, fresh_trace(), leg_rps, secs, seed=29)
            handles[leg] = (name, h, leg_rps)

        per_round: Dict[str, List[Dict[str, Any]]] = \
            {leg: [] for leg in handles}
        for rnd in range(rounds):
            for leg, (name, h, leg_rps) in handles.items():
                print(f"== round {rnd + 1}/{rounds} {leg} @ {leg_rps} "
                      f"rps x {secs}s ==")
                r = _serve_leg(h, fresh_trace(), leg_rps, secs,
                               seed=101 + rnd)
                print(f"  {leg}: {r}")
                per_round[leg].append(r)

        def med(vals: List[float]) -> float:
            vals = sorted(vals)
            return vals[len(vals) // 2]

        legs = {}
        for leg, (name, h, leg_rps) in handles.items():
            rs = per_round[leg]
            agg = {}
            for k in ("p50_ms", "p99_ms", "ttft_p50_ms", "ttft_p99_ms",
                      "rps", "tok_s"):
                # a fully-shed/failed round emits no ttft_* keys
                # (poisson_load saw no streamed completion): aggregate
                # over the rounds that have the series instead of
                # crashing the whole multi-minute run at the end
                vals = [r[k] for r in rs if k in r]
                agg[k] = med(vals) if vals else None
            agg["offered"] = sum(r["offered"] for r in rs)
            agg["completed"] = sum(r["completed"] for r in rs)
            agg["failed"] = sum(r["failed"] for r in rs)
            agg["shed"] = sum(r["shed"] for r in rs)
            agg["rounds"] = rs
            st = serve.detailed_status()["applications"][name][
                "deployments"]["KV"]["stats"]
            for k in ("kv_hits", "kv_misses", "kv_hit_rate", "kv_bytes",
                      "kv_evictions"):
                if k in st:
                    agg[k] = st[k]
            legs[leg] = agg
            print(f"{leg} (median of {rounds}): "
                  f"{ {k: v for k, v in agg.items() if k != 'rounds'} }")
            serve.delete(name)
        artifact["serve"] = legs
        artifact["serve_method"] = (
            f"{rounds} interleaved cold/warm/warm_2x rounds of {secs}s "
            "each; per-leg stats are the MEDIAN across rounds (ambient "
            "load on the shared CPU box drifts slice-to-slice; "
            "interleaving + median keeps the A/B at equal conditions)")

        cold, warm, warm2 = legs["cold"], legs["warm"], legs["warm_2x"]
        artifact["ttft_collapse_x_serve"] = round(
            (cold.get("ttft_p50_ms") or 0.0)
            / max(warm.get("ttft_p50_ms") or 1e-9, 1e-9), 2)
        artifact["hits_advancing"] = bool(warm.get("kv_hits", 0) > 0)
        shed_budget = cold["failed"] + cold["shed"]
        artifact["p99_held_at_2x"] = bool(
            warm2["p99_ms"] is not None and cold["p99_ms"] is not None
            and warm2["p99_ms"] <= max(cold["p99_ms"], 1.0)
            and warm2["failed"] + warm2["shed"] <= shed_budget)
        artifact["ttft_collapse_x_engine"] = \
            artifact["engine_ttft"]["ttft_collapse_x"]
        artifact["collapse_ge_5x"] = bool(
            artifact["engine_ttft"]["ttft_collapse_x"] >= 5.0)
        print(f"engine collapse x{artifact['ttft_collapse_x_engine']} "
              f"(>=5x: {artifact['collapse_ge_5x']}); serve collapse "
              f"x{artifact['ttft_collapse_x_serve']}; hits advancing: "
              f"{artifact['hits_advancing']}; p99 held at 2x load: "
              f"{artifact['p99_held_at_2x']}")
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(artifact, f, indent=1, sort_keys=True)
        print(f"artifact -> {out_path}")
    finally:
        try:
            serve.shutdown()
        except Exception:  # noqa: BLE001 — bench teardown
            pass
        if started_here:
            ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
