"""``rt microbenchmark`` — core-ops throughput/latency sweep.

Reference analog: ``ray microbenchmark`` (``_private/ray_perf.py:93-311``):
small-op throughputs for put/get, task submission, and actor calls, printed
one line per benchmark.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, List, Tuple

import numpy as np


def _timeit(name: str, fn: Callable[[], int], min_seconds: float = 2.0
            ) -> Tuple[str, float]:
    """fn() runs one batch and returns the op count; loops until the clock
    budget is spent, reports ops/s."""
    fn()  # warmup
    total_ops = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < min_seconds:
        total_ops += fn()
    dt = time.perf_counter() - t0
    rate = total_ops / dt
    print(f"{name:55s} {rate:12.1f} ops/s")
    return name, rate


def broadcast_bench(size_mb: int = 100, getters: int = 4,
                    rounds: int = 3) -> Dict[str, float]:
    """``broadcast_100mb``: 1 put, N same-node getters — the object-plane
    fan-out scenario (weight shipping, batch broadcast). Two transports:

      - **mmap**: each getter is a worker task whose ``get`` resolves the
        payload through the node's shm store — a zero-copy read-only
        mmap (pickle-5 buffers alias the mapping).
      - **chunked-rpc**: the same bytes pulled through the raylet's
        ``get_object_chunk`` hand-copy path (what a no-shm client pays,
        and what every transfer paid before the shm plane).

    Reports aggregate GB/s (N x size / wall). Sizing via
    ``RT_BCAST_MB`` / ``RT_BCAST_GETTERS`` when run from the CLI sweep.
    """
    import asyncio

    import ray_tpu

    size = size_mb * 1024 * 1024
    payload = np.random.default_rng(0).integers(
        0, 255, size=size, dtype=np.uint8)
    ref = ray_tpu.put(payload)

    @ray_tpu.remote(num_cpus=0)
    def reader(refs):
        # ref wrapped in a list so the GET runs in the task (an arg ref
        # would be resolved by the arg-fetch path before user code)
        arr = ray_tpu.get(refs[0])
        return int(arr.nbytes)

    # warmup: spawn the getter workers + first-touch the mapping
    assert ray_tpu.get([reader.remote([ref]) for _ in range(getters)]) \
        == [size] * getters

    best_mmap = 0.0
    for _ in range(rounds):
        t0 = time.perf_counter()
        got = ray_tpu.get([reader.remote([ref]) for _ in range(getters)])
        dt = time.perf_counter() - t0
        assert got == [size] * getters
        best_mmap = max(best_mmap, getters * size / dt / 1e9)

    # chunked-RPC control: the raylet serves the same object in bounded
    # chunks (client-mode transport) — concurrent pulls on the io loop
    backend = ray_tpu.global_worker()._require_backend()
    oid_hex = ref.hex()

    async def pull_n():
        await asyncio.gather(*[backend._download_object(oid_hex, None)
                               for _ in range(getters)])

    backend.io.run(pull_n())  # warmup
    best_rpc = 0.0
    for _ in range(rounds):
        t0 = time.perf_counter()
        backend.io.run(pull_n())
        dt = time.perf_counter() - t0
        best_rpc = max(best_rpc, getters * size / dt / 1e9)

    out = {"size_mb": float(size_mb), "getters": float(getters),
           "mmap_gb_s": round(best_mmap, 2),
           "chunked_rpc_gb_s": round(best_rpc, 2),
           "speedup": round(best_mmap / max(best_rpc, 1e-9), 1)}
    print(f"{'broadcast %dMB x%d mmap (zero-copy shm)' % (size_mb, getters):55s}"
          f" {best_mmap:10.2f} GB/s")
    print(f"{'broadcast %dMB x%d chunked-RPC (hand-copy)' % (size_mb, getters):55s}"
          f" {best_rpc:10.2f} GB/s   (mmap speedup x{out['speedup']})")
    return out


def main(args=None) -> int:
    import ray_tpu

    started_here = False
    if not ray_tpu.is_initialized():
        ray_tpu.init()
        started_here = True
    results: List[Tuple[str, float]] = []

    try:
        # ---- object plane ---------------------------------------------------
        small = b"x" * 1024
        results.append(_timeit(
            "put small object (1KB, memory store)",
            lambda: sum(1 for _ in range(100) if ray_tpu.put(small))))

        big = np.zeros(256 * 1024, dtype=np.float32)  # 1MB -> plasma
        results.append(_timeit(
            "put 1MB numpy (plasma)",
            lambda: sum(1 for _ in range(20) if ray_tpu.put(big))))

        ref_small = ray_tpu.put(small)
        results.append(_timeit(
            "get small object",
            lambda: sum(1 for _ in range(100)
                        if ray_tpu.get(ref_small) is not None)))

        ref_big = ray_tpu.put(big)
        results.append(_timeit(
            "get 1MB numpy (zero-copy shm)",
            lambda: sum(1 for _ in range(50)
                        if ray_tpu.get(ref_big) is not None)))

        # ---- tasks -----------------------------------------------------------
        @ray_tpu.remote
        def nop():
            return b"ok"

        def task_batch():
            ray_tpu.get([nop.remote() for _ in range(20)])
            return 20

        results.append(_timeit("task submit+get (pipelined x20)", task_batch))

        # ---- actors ----------------------------------------------------------
        @ray_tpu.remote
        class A:
            def m(self):
                return b"ok"

        a = A.remote()
        ray_tpu.get(a.m.remote())

        def actor_sync():
            for _ in range(20):
                ray_tpu.get(a.m.remote())
            return 20

        results.append(_timeit("actor call sync (1 in flight)", actor_sync))

        def actor_async():
            ray_tpu.get([a.m.remote() for _ in range(50)])
            return 50

        results.append(_timeit("actor call async (50 in flight)", actor_async))

        # ---- object-plane broadcast -----------------------------------------
        broadcast_bench(
            size_mb=int(os.environ.get("RT_BCAST_MB", "100")),
            getters=int(os.environ.get("RT_BCAST_GETTERS", "4")))
    finally:
        if started_here:
            ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
