"""``rt microbenchmark`` — core-ops throughput/latency sweep.

Reference analog: ``ray microbenchmark`` (``_private/ray_perf.py:93-311``):
small-op throughputs for put/get, task submission, and actor calls, printed
one line per benchmark.
"""

from __future__ import annotations

import time
from typing import Callable, List, Tuple

import numpy as np


def _timeit(name: str, fn: Callable[[], int], min_seconds: float = 2.0
            ) -> Tuple[str, float]:
    """fn() runs one batch and returns the op count; loops until the clock
    budget is spent, reports ops/s."""
    fn()  # warmup
    total_ops = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < min_seconds:
        total_ops += fn()
    dt = time.perf_counter() - t0
    rate = total_ops / dt
    print(f"{name:55s} {rate:12.1f} ops/s")
    return name, rate


def main(args=None) -> int:
    import ray_tpu

    started_here = False
    if not ray_tpu.is_initialized():
        ray_tpu.init()
        started_here = True
    results: List[Tuple[str, float]] = []

    try:
        # ---- object plane ---------------------------------------------------
        small = b"x" * 1024
        results.append(_timeit(
            "put small object (1KB, memory store)",
            lambda: sum(1 for _ in range(100) if ray_tpu.put(small))))

        big = np.zeros(256 * 1024, dtype=np.float32)  # 1MB -> plasma
        results.append(_timeit(
            "put 1MB numpy (plasma)",
            lambda: sum(1 for _ in range(20) if ray_tpu.put(big))))

        ref_small = ray_tpu.put(small)
        results.append(_timeit(
            "get small object",
            lambda: sum(1 for _ in range(100)
                        if ray_tpu.get(ref_small) is not None)))

        ref_big = ray_tpu.put(big)
        results.append(_timeit(
            "get 1MB numpy (zero-copy shm)",
            lambda: sum(1 for _ in range(50)
                        if ray_tpu.get(ref_big) is not None)))

        # ---- tasks -----------------------------------------------------------
        @ray_tpu.remote
        def nop():
            return b"ok"

        def task_batch():
            ray_tpu.get([nop.remote() for _ in range(20)])
            return 20

        results.append(_timeit("task submit+get (pipelined x20)", task_batch))

        # ---- actors ----------------------------------------------------------
        @ray_tpu.remote
        class A:
            def m(self):
                return b"ok"

        a = A.remote()
        ray_tpu.get(a.m.remote())

        def actor_sync():
            for _ in range(20):
                ray_tpu.get(a.m.remote())
            return 20

        results.append(_timeit("actor call sync (1 in flight)", actor_sync))

        def actor_async():
            ray_tpu.get([a.m.remote() for _ in range(50)])
            return 50

        results.append(_timeit("actor call async (50 in flight)", actor_async))
    finally:
        if started_here:
            ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
