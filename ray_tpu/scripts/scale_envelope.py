"""``rt scale-envelope`` — one-host scalability envelope.

Reference analog: ``release/benchmarks/README.md:7-31`` (the committed
scalability envelope: 10k+ simultaneous tasks, 40k actors, 1M queued tasks,
10k object args, 1k PGs — measured on a 64x64-core cloud cluster) and the
drivers in ``release/benchmarks/distributed/test_many_tasks.py``.

This is the single-host, scaled-down analog: each scenario is time-bounded,
isolated (one failing scenario never discards the others' numbers), and
reports an achieved count + rate so the asyncio-Python control plane's
limits are MEASURED rather than assumed (VERDICT r4 #3 — the evidence the
Python-raylet redesign owes). Writes one JSON document; the driver commits
it as SCALE_r{N}.json.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict


def _preserve(out: Dict[str, Any]) -> None:
    """Self-preservation (the bench.py RT_BENCH_PRESERVE idiom): every
    finished scenario atomically refreshes the artifact, so a later
    scenario wedging cannot discard the numbers already measured."""
    path = os.environ.get("RT_SCALE_PRESERVE", "")
    if not path:
        return
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(out, indent=2) + "\n")
        os.replace(tmp, path)
    except OSError:
        pass


def _scenario(out: Dict[str, Any], name: str):
    """Decorator-ish context: run fn, record result or error under name."""

    class _Ctx:
        def __enter__(self):
            import sys

            print(f"[scale-envelope] {name} ...", file=sys.stderr,
                  flush=True)
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, et, ev, tb):
            import sys

            wall = round(time.perf_counter() - self.t0, 2)
            out.setdefault("scenarios", {}).setdefault(name, {})[
                "wall_s"] = wall
            print(f"[scale-envelope] {name} done in {wall}s",
                  file=sys.stderr, flush=True)
            if ev is not None:
                out["scenarios"][name]["error"] = f"{et.__name__}: {ev}"[:300]
                _preserve(out)
                return True  # isolate: swallow, keep other scenarios
            _preserve(out)
            return False

        def record(self, **kv):
            out.setdefault("scenarios", {}).setdefault(name, {}).update(kv)

    return _Ctx()


def _placement_balance(out: Dict[str, Any]) -> None:
    """Scenario 8: skewed submit across a 2-node fake-resource cluster.

    Every driver submission lands on the small head raylet; the per-class
    spill heuristic must shed the excess to the big node. While the flood
    drains we sample the GCS balance tick (``sched_balance`` — the same
    series behind ``rt_sched_node_imbalance`` and ``rt sched balance``):
    the committed evidence is the imbalance-CoV series plus the spillback
    placement receipts the hops left behind."""
    import ray_tpu
    from ray_tpu.cluster.cluster_utils import Cluster

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    with _scenario(out, "placement_balance") as sc:
        cluster = Cluster(initialize_head=True,
                          head_node_args={"num_cpus": 1})
        try:
            cluster.add_node(num_cpus=4)
            cluster.connect_driver()

            @ray_tpu.remote
            def spin():
                # long enough that the skewed backlog spans several 1 s
                # balance ticks — the series must show spike AND recovery
                time.sleep(0.2)
                return 0

            backend = ray_tpu.global_worker()._require_backend()

            def _gcs(method, payload):
                return backend.io.run(backend._gcs.call(method, payload))

            n = int(os.environ.get("RT_SCALE_BALANCE_TASKS", "300"))
            pending = [spin.remote() for _ in range(n)]
            covs = [float(_gcs("sched_balance", {}).get("cov") or 0.0)]
            deadline = time.perf_counter() + 90.0
            while pending and time.perf_counter() < deadline:
                _, pending = ray_tpu.wait(
                    pending, num_returns=len(pending), timeout=1.0)
                covs.append(
                    float(_gcs("sched_balance", {}).get("cov") or 0.0))
            bal = _gcs("sched_balance", {"limit": 120})
            series = [round(float(h.get("cov") or 0.0), 3)
                      for h in bal.get("history") or ()]
            spills = _gcs("list_placement_events",
                          {"kind": "spillback", "limit": 1000}) or []
            sc.record(
                nodes=2, tasks=n, drained=n - len(pending),
                cov_peak=round(max(covs), 3),
                cov_final=round(covs[-1], 3),
                cov_series=series[-40:],
                spillback_records=len(spills),
                spillback_tasks=sum(int(e.get("count", 1))
                                    for e in spills),
                decisions_total=len(_gcs("list_placement_events",
                                         {"limit": 2000}) or []),
            )
        finally:
            cluster.shutdown()


def run_envelope(actor_target: int = 1000, queued_target: int = 10_000,
                 get_objects: int = 1000, pg_target: int = 100,
                 task_args_target: int = 1000,
                 actor_budget_s: float = 120.0,
                 placement_only: bool = False) -> Dict[str, Any]:
    import numpy as np

    import ray_tpu

    out: Dict[str, Any] = {
        "hardware": {"cpus": os.cpu_count()},
        "reference": "release/benchmarks/README.md:7-31 (64x64-core "
                     "cluster); this is the 1-host analog",
    }
    try:
        import psutil  # noqa: F401 — optional

        out["hardware"]["mem_gb"] = round(
            psutil.virtual_memory().total / 1e9, 1)
    except Exception:  # noqa: BLE001
        pass

    if placement_only:
        _placement_balance(out)
        return out

    # Generous fake resources: the envelope exercises the CONTROL PLANE
    # (scheduler, GCS, object plane), not arithmetic — same trick as the
    # reference's fake-resource cluster tests.
    ray_tpu.init(num_cpus=max(16, os.cpu_count() or 1))
    try:
        # ---- 1. sustained task throughput -------------------------------
        @ray_tpu.remote
        def nop():
            return 0

        with _scenario(out, "tasks_per_sec") as sc:
            # warm the FULL worker pool (a wide round boots every slot the
            # spawn throttle allows) so the timed loop measures
            # steady-state dispatch, not process boots
            ray_tpu.get([nop.remote() for _ in range(200)])
            ray_tpu.get([nop.remote() for _ in range(200)])
            n_done = 0
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < 10.0:
                ray_tpu.get([nop.remote() for _ in range(200)])
                n_done += 200
            dt = time.perf_counter() - t0
            sc.record(tasks=n_done, tasks_per_sec=round(n_done / dt, 1))

        # ---- 2. queued tasks on one node --------------------------------
        # Submission outruns execution (0-CPU nop workers drain slowly on
        # purpose via a short sleep): measures how many tasks the raylet
        # queue holds while staying responsive, and the submission rate.
        @ray_tpu.remote
        def tiny_sleep():
            time.sleep(0.001)
            return 0

        with _scenario(out, "queued_tasks") as sc:
            t0 = time.perf_counter()
            refs = [tiny_sleep.remote() for _ in range(queued_target)]
            submit_dt = time.perf_counter() - t0
            # responsiveness probe while the queue drains
            probe_t0 = time.perf_counter()
            ray_tpu.get(nop.remote())
            probe_ms = (time.perf_counter() - probe_t0) * 1000
            ray_tpu.get(refs)  # full drain
            drain_dt = time.perf_counter() - t0
            sc.record(queued=queued_target,
                      submit_per_sec=round(queued_target / submit_dt, 1),
                      probe_latency_ms=round(probe_ms, 1),
                      drain_tasks_per_sec=round(queued_target / drain_dt, 1))

        # ---- 3. many objects in one get ---------------------------------
        with _scenario(out, "get_many_objects") as sc:
            refs = [ray_tpu.put(i) for i in range(get_objects)]
            t0 = time.perf_counter()
            vals = ray_tpu.get(refs)
            get_dt = time.perf_counter() - t0
            assert vals[-1] == get_objects - 1
            sc.record(objects=get_objects,
                      get_wall_s=round(get_dt, 3),
                      objects_per_sec=round(get_objects / get_dt, 1))

        # ---- 4. many object args to a single task -----------------------
        @ray_tpu.remote
        def count_args(*args):
            return len(args)

        with _scenario(out, "object_args_single_task") as sc:
            refs = [ray_tpu.put(i) for i in range(task_args_target)]
            t0 = time.perf_counter()
            got = ray_tpu.get(count_args.remote(*refs))
            sc.record(args=task_args_target, resolved=got,
                      wall_s=round(time.perf_counter() - t0, 3))
            assert got == task_args_target

        # ---- 5. 100MB object broadcast to N tasks -----------------------
        @ray_tpu.remote
        def touch(arr):
            return int(arr[0]) + arr.nbytes

        with _scenario(out, "broadcast_100mb") as sc:
            big = np.zeros(25_000_000, dtype=np.float32)  # 100 MB
            ref = ray_tpu.put(big)
            t0 = time.perf_counter()
            ray_tpu.get([touch.remote(ref) for _ in range(8)])
            dt = time.perf_counter() - t0
            sc.record(consumers=8, wall_s=round(dt, 3),
                      gb_per_sec=round(8 * big.nbytes / 1e9 / dt, 2))

        # ---- 6. live actors ---------------------------------------------
        # Each actor is a real worker process (like the reference): create
        # until the target or the time budget, verify every one responds.
        @ray_tpu.remote(num_cpus=0)
        class Member:
            def ping(self):
                return os.getpid()

        with _scenario(out, "live_actors") as sc:
            actors = []
            t0 = time.perf_counter()
            # small batches so the time budget is honored on a starved box
            # (a 50-wide batch can alone exceed the budget on 1 core; the
            # check between batches would then never fire)
            batch = 10
            while (len(actors) < actor_target
                   and time.perf_counter() - t0 < actor_budget_s):
                new = [Member.remote() for _ in range(
                    min(batch, actor_target - len(actors)))]
                # gate on liveness so we count REAL actors, not queued specs
                ray_tpu.get([a.ping.remote() for a in new])
                actors.extend(new)
                print(f"[scale-envelope] actors: {len(actors)} "
                      f"({time.perf_counter() - t0:.0f}s)",
                      file=__import__("sys").stderr, flush=True)
            create_dt = time.perf_counter() - t0
            t0 = time.perf_counter()
            pids = ray_tpu.get([a.ping.remote() for a in actors])
            call_dt = time.perf_counter() - t0
            sc.record(actors=len(actors),
                      distinct_pids=len(set(pids)),
                      create_per_sec=round(len(actors) / create_dt, 1),
                      fanout_call_wall_s=round(call_dt, 3),
                      calls_per_sec=round(len(actors) / call_dt, 1))
            for a in actors:
                ray_tpu.kill(a)

        # ---- 6b. actor creation from a WARM pool (prestart/adoption) ----
        # The live_actors leg above pays interpreter boot per actor (the
        # "prestart off" number — SCALE_r05's 0.4/s floor). Here the idle
        # pool is populated first (a wide task round releases workers into
        # it), so creation should ADOPT pooled workers instead of forking:
        # the "prestart on" number.
        with _scenario(out, "actors_warm_pool") as sc:
            ray_tpu.get([nop.remote() for _ in range(64)])
            time.sleep(0.5)  # releases settle into the idle pool
            n = min(20, actor_target)
            t0 = time.perf_counter()
            actors = [Member.remote() for _ in range(n)]
            ray_tpu.get([a.ping.remote() for a in actors])
            create_dt = time.perf_counter() - t0
            stats = ray_tpu.global_worker()._require_backend().io.run(
                ray_tpu.global_worker()._require_backend()._raylet.call(
                    "node_stats", {}))
            warm = (stats.get("sched") or {}).get("warm") or {}
            sc.record(actors=n,
                      create_per_sec=round(n / create_dt, 1),
                      actor_adoptions=warm.get("actor_adoptions", 0),
                      warm_hits=warm.get("warm_hits", 0),
                      cold_spawns=warm.get("cold_spawns", 0))
            for a in actors:
                ray_tpu.kill(a)

        # ---- 6c. serve under load: continuous vs static batching --------
        # The ROADMAP item 2 envelope leg: open-loop Poisson arrivals at
        # equal offered load against (a) the live ContinuousBatcher
        # deployment (slot admission, fused rowwise decode, streamed
        # tokens) and (b) the @serve.batch control provisioned for its
        # longest admissible request. Heterogeneous decode lengths are
        # the point: the batch-boundary control decodes max_new for
        # EVERY flush; slot admission decodes what each request asked.
        with _scenario(out, "serve_under_load") as sc:
            from ray_tpu import serve
            from ray_tpu.serve.llm import cb_vs_static_load

            short_t, long_t, frac = 2, 192, 0.08
            rps = float(os.environ.get("RT_SCALE_SERVE_RPS", "10"))
            secs = float(os.environ.get("RT_SCALE_SERVE_SECS", "10"))
            try:
                results = cb_vs_static_load(
                    preset="debug", slots=8, max_len=256,
                    decode_stride=16, prompt_len=8,
                    short_tokens=short_t, long_tokens=long_t,
                    long_frac=frac, rps=rps, duration_s=secs,
                    num_proxies=2, route_base="env")
                for leg, r in results.items():
                    sc.record(**{f"{leg}_{k}": r[k] for k in
                                 ("completed", "failed", "shed", "rps",
                                  "tok_s", "p50_ms", "p99_ms")})
                sc.record(offered_rps=rps, short_tokens=short_t,
                          long_tokens=long_t, long_frac=frac,
                          proxies=2,
                          p99_ratio_cb_vs_static=round(
                              results["continuous"]["p99_ms"]
                              / max(1e-3, results["static"]["p99_ms"]),
                              3))
            finally:
                try:
                    serve.shutdown()
                except Exception:  # noqa: BLE001
                    pass

        # ---- 7. placement-group churn + simultaneous PGs ----------------
        from ray_tpu.util.placement_group import (placement_group,
                                                  remove_placement_group)

        with _scenario(out, "placement_groups") as sc:
            pgs = []
            t0 = time.perf_counter()
            for _ in range(pg_target):
                pg = placement_group([{"CPU": 0.01}], strategy="PACK")
                pg.wait(timeout=30)
                pgs.append(pg)
            create_dt = time.perf_counter() - t0
            n_live = len(pgs)
            t0 = time.perf_counter()
            for pg in pgs:
                remove_placement_group(pg)
            remove_dt = time.perf_counter() - t0
            sc.record(simultaneous_pgs=n_live,
                      create_per_sec=round(n_live / create_dt, 1),
                      remove_per_sec=round(n_live / remove_dt, 1))
    finally:
        ray_tpu.shutdown()

    # ---- 8. cross-node placement balance (own 2-node cluster) -----------
    _placement_balance(out)
    return out


def main(args=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="rt scale-envelope")
    ap.add_argument("--actors", type=int, default=1000)
    ap.add_argument("--queued", type=int, default=10_000)
    ap.add_argument("--objects", type=int, default=1000)
    ap.add_argument("--pgs", type=int, default=100)
    ap.add_argument("--task-args", type=int, default=1000)
    ap.add_argument("--actor-budget-s", type=float, default=120.0)
    ap.add_argument("--placement-only", action="store_true",
                    help="run only the placement_balance scenario "
                         "(2-node skewed-submit cluster)")
    ap.add_argument("--out", type=str, default="")
    ns = ap.parse_args(args)

    result = run_envelope(actor_target=ns.actors, queued_target=ns.queued,
                          get_objects=ns.objects, pg_target=ns.pgs,
                          task_args_target=ns.task_args,
                          actor_budget_s=ns.actor_budget_s,
                          placement_only=ns.placement_only)
    doc = json.dumps(result, indent=2)
    if ns.out:
        with open(ns.out, "w") as f:
            f.write(doc + "\n")
    print(doc)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
