"""Stream-transport A/B bench: push vs pull at equal offered load.

The ROADMAP item-1 acceptance leg: prove the RPC-per-token count on the
streamed serve path collapses to O(1) per request (constant in token
count), and that streamed serve tok/s at N concurrent streams lands
within 1.5x of the raw engine rate on the same box.

Three legs, same model/preset/slot budget:

  1. **raw engine** — ``ContinuousEngine`` driven directly (no serve
     layer): the ceiling the transport is judged against.
  2. **push** — the default transport: one ``stream_subscribe`` RPC,
     then one-way frames (``cluster/stream.py``).
  3. **pull** — ``RT_STREAM_PULL=1``: the PR 9 wide-pull path
     (one ``next_chunks`` actor RPC per 64-token burst).

Plus an RPCs-vs-token-count sweep (the O(1) proof): mean RPCs per
request at several ``max_new_tokens`` for both transports.

Writes the committed artifact (default ``BENCH_STREAM_r07.json``);
env knobs: RT_STREAM_BENCH_STREAMS / _TOKENS / _SLOTS / _OUT.
"""

from __future__ import annotations

import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List


def _engine_leg(preset: str, slots: int, max_len: int, stride: int,
                streams: int, tokens: int) -> Dict[str, Any]:
    """The raw ceiling: N concurrent requests straight into one engine."""
    import jax

    from ray_tpu.models import llama
    from ray_tpu.models.serving import ContinuousEngine

    cfg = llama.PRESETS[preset]
    params = llama.init_params(jax.random.key(0), cfg)
    engine = ContinuousEngine(params, cfg, max_slots=slots,
                              max_len=max_len, decode_stride=stride)
    prompt = list(range(1, 9))
    counts = [0] * streams
    events = [threading.Event() for _ in range(streams)]

    def run_once() -> float:
        for e in events:
            e.clear()
        for i in range(streams):
            counts[i] = 0

            def on_token(burst, i=i):
                for t in burst:
                    if t is None:
                        events[i].set()
                    else:
                        counts[i] += 1

            engine.submit_cb(prompt, tokens, on_token)
        t0 = time.perf_counter()
        for e in events:
            e.wait(timeout=600)
        wall = time.perf_counter() - t0
        assert all(c == tokens for c in counts), counts
        return streams * tokens / wall

    run_once()  # warmup (XLA programs already compiled at engine init)
    tok_s = max(run_once() for _ in range(2))
    engine.shutdown()
    return {"tok_s": round(tok_s, 1), "streams": streams,
            "tokens_per_stream": tokens}


def _serve_leg(handle, streams: int, tokens: int) -> Dict[str, Any]:
    """N concurrent streamed handle requests at equal offered load;
    reports tok/s plus the observed RPCs-per-request distribution."""
    body = {"tokens": list(range(1, 9)), "max_new_tokens": tokens}
    results: List[Dict[str, Any]] = []
    lock = threading.Lock()

    def one() -> None:
        gen = handle.remote(body).result()
        n = sum(1 for _ in gen)
        with lock:
            results.append({"tokens": n, "rpcs": gen._rpcs,
                            "transport": gen._transport})

    with ThreadPoolExecutor(max_workers=streams) as pool:
        # warmup request (replica boot + route) outside the timed window
        one()
        results.clear()
        t0 = time.perf_counter()
        futs = [pool.submit(one) for _ in range(streams)]
        for f in futs:
            f.result()
        wall = time.perf_counter() - t0
    total = sum(r["tokens"] for r in results)
    assert all(r["tokens"] == tokens for r in results), \
        [r["tokens"] for r in results]
    rpcs = sorted(r["rpcs"] for r in results)
    return {"tok_s": round(total / wall, 1), "streams": streams,
            "tokens_per_stream": tokens,
            "transport": results[0]["transport"],
            "rpcs_per_request_mean": round(sum(rpcs) / len(rpcs), 2),
            "rpcs_per_request_max": rpcs[-1]}


def _rpc_scaling(handle, token_counts: List[int], per_n: int = 4
                 ) -> List[Dict[str, Any]]:
    """Mean RPCs per request as token count grows — constant on push,
    linear (1 + ceil(n/64)-ish) on pull."""
    out = []
    for n in token_counts:
        body = {"tokens": list(range(1, 9)), "max_new_tokens": n}
        rpcs = []
        for _ in range(per_n):
            gen = handle.remote(body).result()
            got = sum(1 for _ in gen)
            assert got == n, (got, n)
            rpcs.append(gen._rpcs)
        out.append({"tokens": n,
                    "rpcs_mean": round(sum(rpcs) / len(rpcs), 2)})
    return out


def main(args=None) -> int:
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import continuous_llm_app

    preset = os.environ.get("RT_STREAM_BENCH_PRESET", "debug")
    streams = int(os.environ.get("RT_STREAM_BENCH_STREAMS", "64"))
    tokens = int(os.environ.get("RT_STREAM_BENCH_TOKENS", "64"))
    slots = int(os.environ.get("RT_STREAM_BENCH_SLOTS", "8"))
    stride = int(os.environ.get("RT_STREAM_BENCH_STRIDE", "16"))
    scaling_counts = [16, 64, 256]
    max_len = 16 + max([tokens] + scaling_counts)
    out_path = os.environ.get("RT_STREAM_BENCH_OUT",
                              "BENCH_STREAM_r07.json")

    started_here = False
    if not ray_tpu.is_initialized():
        ray_tpu.init()
        started_here = True
    artifact: Dict[str, Any] = {
        "schema": "rt-stream-bench-1", "preset": preset,
        "t": time.time(),
        "note": ("push vs pull at equal offered load, one replica, "
                 "same engine config; raw engine is the ceiling. "
                 "rpcs_per_request counts handle_request + transport "
                 "RPCs observed by the consumer."),
    }
    try:
        print(f"== raw engine: {streams} streams x {tokens} tok ==")
        artifact["raw_engine"] = _engine_leg(preset, slots, max_len,
                                             stride, streams, tokens)
        print(f"raw engine: {artifact['raw_engine']['tok_s']} tok/s")

        for leg, env in (("push", None), ("pull", "1")):
            if env is None:
                os.environ.pop("RT_STREAM_PULL", None)
            else:
                os.environ["RT_STREAM_PULL"] = env
            app = continuous_llm_app(
                preset, max_slots=slots, max_len=max_len,
                decode_stride=stride, name="CB",
                max_ongoing_requests=2 * streams)
            serve.run(app, name=f"sb-{leg}", route_prefix=f"/sb-{leg}")
            handle = serve.get_deployment_handle("CB", f"sb-{leg}")
            print(f"== serve leg: {leg} ==")
            artifact[leg] = _serve_leg(handle, streams, tokens)
            artifact[leg]["rpc_scaling"] = _rpc_scaling(
                handle, scaling_counts)
            print(f"{leg}: {artifact[leg]['tok_s']} tok/s, "
                  f"rpcs/request mean "
                  f"{artifact[leg]['rpcs_per_request_mean']} "
                  f"scaling {artifact[leg]['rpc_scaling']}")
            serve.delete(f"sb-{leg}")
        os.environ.pop("RT_STREAM_PULL", None)

        raw = artifact["raw_engine"]["tok_s"]
        push = artifact["push"]["tok_s"]
        artifact["push_vs_raw_ratio"] = round(raw / max(push, 1e-9), 3)
        artifact["within_1p5x"] = bool(raw / max(push, 1e-9) <= 1.5)
        sc = artifact["push"]["rpc_scaling"]
        artifact["push_rpcs_constant"] = bool(
            max(s["rpcs_mean"] for s in sc)
            - min(s["rpcs_mean"] for s in sc) < 1.0)
        print(f"push {push} tok/s vs raw {raw} tok/s "
              f"(x{artifact['push_vs_raw_ratio']} gap, "
              f"within 1.5x: {artifact['within_1p5x']}); "
              f"push rpcs constant in token count: "
              f"{artifact['push_rpcs_constant']}")
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(artifact, f, indent=1, sort_keys=True)
        print(f"artifact -> {out_path}")
    finally:
        try:
            serve.shutdown()
        except Exception:  # noqa: BLE001 — bench teardown
            pass
        if started_here:
            ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
