"""ASAN/UBSAN harness for the rt_native C extension.

Reference analog: the bazel ``--config=asan`` / ``--config=tsan`` CI
builds exercised over ``src/ray`` (SURVEY.md §4 sanitizers row). Here the
native surface is one translation unit, so the harness (1) rebuilds it
with ``-fsanitize=address,undefined -fno-sanitize-recover=all``, then (2)
runs a worst-case exercise of every export in a subprocess with libasan
preloaded (CPython itself isn't instrumented, so the runtime library must
be LD_PRELOADed; leak detection is off because the interpreter's own
arena allocations would drown real reports).

Run: ``python -m ray_tpu.scripts.sanitize_native`` — exits nonzero on any
sanitizer report or smoke failure. Wired as a slow-marked test in
``tests/test_sanitize_native.py``.
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig
import tempfile

_SMOKE = r"""
import importlib.util
import os
import sys

so, workdir = sys.argv[1], sys.argv[2]
# the spec name must match the extension's PyInit_rt_native symbol
spec = importlib.util.spec_from_file_location("rt_native", so)
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)

# -- crc32c: empty / tiny / unaligned views / large ---------------------
assert mod.crc32c(b"") == 0
big = os.urandom(1 << 20)
full = mod.crc32c(big)
# incremental == one-shot (exercises the init path)
half = mod.crc32c(big[: 1 << 19])
assert mod.crc32c(big[1 << 19:], half) == full
for off in range(1, 9):  # unaligned starts
    mod.crc32c(memoryview(big)[off:])
assert mod.crc32c(b"123456789") == 0xE3069283  # published check value

# -- memory_info / process probes ---------------------------------------
info = mod.memory_info()
assert info["total"] > 0 and 0 <= info["used"] <= info["total"]
assert mod.process_rss(os.getpid()) > 0
mod.process_rss(99999999)  # nonexistent pid must not crash
mems = mod.process_memory([os.getpid(), 99999999])
assert any(p == os.getpid() and rss > 0 for p, rss in mems)

# -- LogKV lifecycle: put/get/delete/compact/replay ---------------------
path = os.path.join(workdir, "kv.log")
kv = mod.LogKV(path)
vals = {}
for i in range(500):
    k = f"key-{i % 97}"
    v = os.urandom(1 + (i * 37) % 4096)
    kv.put(k, v)
    vals[k] = v
for i in range(0, 97, 3):
    kv.delete(f"key-{i}")
    vals.pop(f"key-{i}", None)
kv.sync()
assert sorted(kv.keys()) == sorted(vals)
for k, v in vals.items():
    assert kv.get(k) == v
kv.compact()
assert sorted(kv.keys()) == sorted(vals)
kv.close()

# reopen replays the compacted log
kv2 = mod.LogKV(path)
assert sorted(kv2.keys()) == sorted(vals)
kv2.close()

# torn tail: truncate mid-record, replay must stop cleanly, and the next
# append must recover the file
with open(path, "rb") as f:
    data = f.read()
with open(path, "wb") as f:
    f.write(data[: len(data) - 7])
kv3 = mod.LogKV(path)
kv3.put("after-torn", b"x" * 128)
assert kv3.get("after-torn") == b"x" * 128
kv3.close()
print("SMOKE_OK")
"""


def run(verbose: bool = True) -> int:
    from ray_tpu._native.build import SRC

    import shutil

    if shutil.which("g++") is None:
        print("sanitize_native: g++ unavailable; skipping",
              file=sys.stderr)
        return 0
    libasan = subprocess.run(
        ["g++", "-print-file-name=libasan.so"],
        capture_output=True, text=True).stdout.strip()
    if not os.path.isabs(libasan):
        print("sanitize_native: g++/libasan unavailable; skipping",
              file=sys.stderr)
        return 0

    with tempfile.TemporaryDirectory(prefix="rt_sanitize_") as tmp:
        so = os.path.join(tmp, "rt_native_asan.so")
        include = sysconfig.get_paths()["include"]
        cmd = ["g++", "-O1", "-g", "-std=c++17", "-fPIC", "-shared",
               "-Wall", "-fsanitize=address,undefined",
               "-fno-sanitize-recover=all", f"-I{include}", SRC, "-o", so]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            print(f"sanitized build failed:\n{proc.stderr[-2000:]}",
                  file=sys.stderr)
            return 1
        env = dict(os.environ)
        env["LD_PRELOAD"] = libasan
        env["ASAN_OPTIONS"] = ("detect_leaks=0:abort_on_error=1:"
                               "allocator_may_return_null=1")
        env["UBSAN_OPTIONS"] = "print_stacktrace=1:halt_on_error=1"
        proc = subprocess.run(
            [sys.executable, "-c", _SMOKE, so, tmp],
            capture_output=True, text=True, env=env, timeout=300)
        report = proc.stdout + proc.stderr
        failed = (proc.returncode != 0 or "SMOKE_OK" not in proc.stdout
                  or "ERROR: AddressSanitizer" in report
                  or "runtime error" in report)
        if failed or verbose:
            print(report[-4000:], file=sys.stderr if failed else sys.stdout)
        if failed:
            print("sanitize_native: FAILED", file=sys.stderr)
            return 1
        print("sanitize_native: OK (asan+ubsan clean)")
        return 0


if __name__ == "__main__":
    sys.exit(run())
