"""Flash attention as pallas TPU kernels (fwd + bwd), with LSE output.

The memory-bound softmax(QK^T)V chain rewritten as the streaming-softmax
algorithm: the [seq, seq] score matrix never materializes in HBM; each grid
step keeps a [block_q, head_dim] accumulator plus running (max, sum) rows in
VMEM. The backward pass is two kernels (dq; dkv) over recomputed score
blocks, using the saved log-sum-exp instead of the softmax weights.

This replaces what the reference delegates to torch/CUDA libraries (it has no
attention kernels of its own — SURVEY.md §5 "Long-context: absent"); here it
is a first-class op because ring/context parallelism composes from the
``(out, lse)`` form (``ray_tpu/parallel/context.py``).

Layout: wrappers take [batch, seq, heads, head_dim] (framework convention),
kernels run on [batch*heads, seq, head_dim]. ``q_position_offset`` is a
dynamic scalar (SMEM) so ring attention can slide the causal mask per step.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.ops.attention import NEG_INF

# jax >= 0.6 spells it CompilerParams; 0.4.x TPUCompilerParams (same kwargs).
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def _needs_interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------- forward

def _fwd_kernel(qoff_ref, q_ref, k_ref, v_ref,  # inputs
                o_ref, lse_ref,                 # outputs
                m_scr, l_scr, acc_scr,          # scratch
                *, scale, causal, block_q, block_k, kv_len):
    kk = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kk == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q = q_ref[0]                                   # [bq, d]
    k = k_ref[0]                                   # [bk, d]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # [bq, bk]

    qi = pl.program_id(1)
    kpos = kk * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    mask = kpos < kv_len                           # key padding
    if causal:
        qpos = (qi * block_q + qoff_ref[0]
                + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0))
        mask = mask & (qpos >= kpos)
    s = jnp.where(mask, s, NEG_INF)

    m_prev = m_scr[...]                            # [bq, 1]
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)                         # [bq, bk] fp32
    # Fully-masked rows: m_new stays NEG_INF; exp(NEG_INF - NEG_INF)=1 would
    # poison p, so zero those rows explicitly.
    row_dead = m_new <= NEG_INF / 2
    p = jnp.where(row_dead, 0.0, p)
    alpha = jnp.where(row_dead, 0.0, alpha)

    l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
    m_scr[...] = m_new
    acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(kk == nk - 1)
    def _finish():
        l = l_scr[...]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        lse = jnp.where(l == 0.0, NEG_INF, m_scr[...] + jnp.log(l_safe))
        lse_ref[0] = lse.astype(lse_ref.dtype)


def _flash_fwd_bhsd(q, k, v, q_offset, *, scale, causal, kv_len,
                    block_q, block_k, interpret) -> Tuple[jax.Array, jax.Array]:
    """q,k,v: [bh, s, d] (pre-padded to block multiples); returns (o, lse).

    ``kv_len`` is the TRUE (unpadded) key length — padded keys are masked.
    """
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = sq // block_q, sk // block_k
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_len=kv_len)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q_offset, q, k, v)
    return o, lse


# ---------------------------------------------------------------- backward

def _dq_kernel(qoff_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_scr,
               *, scale, causal, block_q, block_k, kv_len):
    kk = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kk == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    q = q_ref[0]
    k = k_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    kpos = kk * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    mask = kpos < kv_len
    if causal:
        qpos = (pl.program_id(1) * block_q + qoff_ref[0]
                + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0))
        mask = mask & (qpos >= kpos)
    lse = lse_ref[0]                               # [bq, 1]
    p = jnp.where(mask & (lse > NEG_INF / 2), jnp.exp(s - lse), 0.0)
    do = do_ref[0].astype(jnp.float32)
    dp = jax.lax.dot_general(do, v_ref[0].astype(jnp.float32),
                             (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0]) * scale
    dq_scr[...] += jax.lax.dot_general(
        ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(kk == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(qoff_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr,
                *, scale, causal, block_q, block_k, kv_len):
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    q = q_ref[0]
    k = k_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    kk = pl.program_id(1)
    kpos = kk * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    mask = kpos < kv_len
    if causal:
        qpos = (qi * block_q + qoff_ref[0]
                + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0))
        mask = mask & (qpos >= kpos)
    lse = lse_ref[0]                               # [bq, 1]
    p = jnp.where(mask & (lse > NEG_INF / 2), jnp.exp(s - lse), 0.0)
    do = do_ref[0].astype(jnp.float32)
    dv_scr[...] += jax.lax.dot_general(
        p, do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(do, v_ref[0].astype(jnp.float32),
                             (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0]) * scale
    dk_scr[...] += jax.lax.dot_general(
        ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_bhsd(q, k, v, o, lse, do, q_offset, *, scale, causal, kv_len,
                    block_q, block_k, interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = sq // block_q, sk // block_k
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1,
                    keepdims=True)                 # [bh, sq_pad, 1]

    common = dict(scale=scale, causal=causal,
                  block_q=block_q, block_k=block_k, kv_len=kv_len)
    qspec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    rowspec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **common),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            qspec,
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            qspec, rowspec, rowspec,
        ],
        out_specs=[qspec],
        out_shape=[jax.ShapeDtypeStruct((bh, sq, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q_offset, q, k, v, do, lse, delta)[0]

    # dk/dv: grid walks k blocks outer, q blocks inner.
    kspec = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))
    qspec2 = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
    rowspec2 = pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, **common),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            qspec2, kspec, kspec, qspec2, rowspec2, rowspec2,
        ],
        out_specs=[kspec, kspec],
        out_shape=[jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, sk, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q_offset, q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------- public API

def _pad_seq(x, block):
    s = x.shape[1]
    pad = (-s) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


def _prep(q, k, v):
    """[b,s,h,d] -> [b*h, s, d] with GQA kv-head repetition."""
    b, sq, hq, d = q.shape
    hkv = k.shape[2]
    if hq != hkv:
        if hq % hkv:
            raise ValueError(f"q heads {hq} not divisible by kv heads {hkv}")
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    to_bhsd = lambda x: x.transpose(0, 2, 1, 3).reshape(b * hq, x.shape[1], d)
    return to_bhsd(q), to_bhsd(k), to_bhsd(v)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _pick_block(requested: int, seq: int) -> int:
    """Block size: the requested one, shrunk (to a multiple of 8) for short
    sequences so tiny shapes don't pad to 128."""
    return min(requested, _round_up(max(seq, 8), 8))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_core(q, k, v, scale, causal, block_q, block_k, interpret, q_offset):
    return _flash_core_fwd(q, k, v, scale, causal, block_q, block_k,
                           interpret, q_offset)[0]


def _flash_core_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
                    q_offset):
    qoff = jnp.asarray([q_offset], jnp.int32)
    sq, sk = q.shape[1], k.shape[1]
    qp, kp, vp = _pad_seq(q, block_q), _pad_seq(k, block_k), _pad_seq(v, block_k)
    o, lse = _flash_fwd_bhsd(qp, kp, vp, qoff, scale=scale, causal=causal,
                             kv_len=sk, block_q=block_q, block_k=block_k,
                             interpret=interpret)
    return o[:, :sq], (q, k, v, o, lse)


def _flash_core_bwd(scale, causal, block_q, block_k, interpret, q_offset,
                    res, do):
    q, k, v, o_pad, lse = res
    qoff = jnp.asarray([q_offset], jnp.int32)
    sq, sk = q.shape[1], k.shape[1]
    qp, kp, vp = _pad_seq(q, block_q), _pad_seq(k, block_k), _pad_seq(v, block_k)
    dop = _pad_seq(do, block_q)
    dq, dk, dv = _flash_bwd_bhsd(qp, kp, vp, o_pad, lse, dop, qoff,
                                 scale=scale, causal=causal, kv_len=sk,
                                 block_q=block_q, block_k=block_k,
                                 interpret=interpret)
    return dq[:, :sq], dk[:, :sk], dv[:, :sk]


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True,
                    scale: Optional[float] = None,
                    q_offset: int = 0,
                    block_q: int = 128,
                    block_k: int = 128,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Differentiable flash attention over [batch, seq, heads, head_dim].

    Drop-in for ``ray_tpu.ops.attention.mha`` (minus segment_ids/bias — the
    XLA path handles those). ``q_offset``: absolute position of q[0] relative
    to k[0], for decode and ring steps; static here (see
    ``flash_attention_with_lse`` for a traced offset).
    """
    b, sq, hq, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    if interpret is None:
        interpret = _needs_interpret()
    block_q = _pick_block(block_q, sq)
    block_k = _pick_block(block_k, k.shape[1])
    qf, kf, vf = _prep(q, k, v)
    o = _flash_core(qf, kf, vf, scale, causal, block_q, block_k, interpret,
                    q_offset)
    return o.reshape(b, hq, sq, d).transpose(0, 2, 1, 3)


def flash_vjp_chunk(q, k, v, o, do, lse, *,
                    q_offset,
                    causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = 128,
                    block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Per-chunk backward for ring attention.

    Given the GLOBAL (o, lse) of the softmax over all chunks and one k/v
    chunk, returns this chunk's additive contribution (dq_partial, dk, dv).
    Summing dq_partial over chunks (and routing dk/dv home around the ring)
    yields exact gradients, because p = exp(s - lse_global) is the true
    softmax weight. q,k,v,o,do: [b,s,h,d]; lse: [b,h,s]; q_offset may be
    traced.
    """
    b, sq, hq, d = q.shape
    hkv = k.shape[2]
    scale = scale if scale is not None else d ** -0.5
    if interpret is None:
        interpret = _needs_interpret()
    block_q = _pick_block(block_q, sq)
    block_k = _pick_block(block_k, k.shape[1])
    sk = k.shape[1]
    qf, kf, vf = _prep(q, k, v)
    to_bhsd = lambda x: x.transpose(0, 2, 1, 3).reshape(b * hq, x.shape[1], d)
    of, dof = to_bhsd(o), to_bhsd(do)
    lsef = lse.reshape(b * hq, sq, 1)
    qoff = jnp.asarray(q_offset, jnp.int32).reshape(1)

    qp = _pad_seq(qf, block_q)
    kp, vp = _pad_seq(kf, block_k), _pad_seq(vf, block_k)
    op, dop = _pad_seq(of, block_q), _pad_seq(dof, block_q)
    lsep = jnp.pad(lsef, ((0, 0), (0, qp.shape[1] - sq), (0, 0)),
                   constant_values=NEG_INF)
    dq, dk, dv = _flash_bwd_bhsd(qp, kp, vp, op, lsep, dop, qoff,
                                 scale=scale, causal=causal, kv_len=sk,
                                 block_q=block_q, block_k=block_k,
                                 interpret=interpret)
    from_bhsd = lambda x, s_: x[:, :s_].reshape(b, hq, s_, d).transpose(0, 2, 1, 3)
    dq, dk, dv = from_bhsd(dq, sq), from_bhsd(dk, sk), from_bhsd(dv, sk)
    if hq != hkv:
        rep = hq // hkv
        dk = dk.reshape(b, sk, hkv, rep, d).sum(axis=3)
        dv = dv.reshape(b, sk, hkv, rep, d).sum(axis=3)
    return dq, dk, dv


def flash_attention_with_lse(q: jax.Array, k: jax.Array, v: jax.Array, *,
                             causal: bool = True,
                             scale: Optional[float] = None,
                             q_offset=0,
                             block_q: int = 128,
                             block_k: int = 128,
                             interpret: Optional[bool] = None
                             ) -> Tuple[jax.Array, jax.Array]:
    """(out [b,s,h,d], lse [b,h,s]) — the composable form for ring attention.

    Forward-only through the kernel (ring attention builds its VJP by
    recomputation); ``q_offset`` may be a traced scalar.
    """
    b, sq, hq, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    if interpret is None:
        interpret = _needs_interpret()
    block_q = _pick_block(block_q, sq)
    block_k = _pick_block(block_k, k.shape[1])
    qf, kf, vf = _prep(q, k, v)
    qoff = jnp.asarray(q_offset, jnp.int32).reshape(1)
    sk = kf.shape[1]
    qp, kp, vp = _pad_seq(qf, block_q), _pad_seq(kf, block_k), _pad_seq(vf, block_k)
    o, lse = _flash_fwd_bhsd(qp, kp, vp, qoff, scale=scale, causal=causal,
                             kv_len=sk, block_q=block_q, block_k=block_k,
                             interpret=interpret)
    o = o[:, :sq].reshape(b, hq, sq, d).transpose(0, 2, 1, 3)
    lse = lse[:, :sq, 0].reshape(b, hq, sq)
    return o, lse
