"""Pallas TPU kernels for the hot ops (flash attention & friends).

These kernels override the XLA-path reference implementations in
``ray_tpu/ops/`` on real TPUs; every kernel also runs in pallas interpret
mode so CPU CI exercises identical code.
"""

from ray_tpu.ops.pallas.flash import flash_attention, flash_attention_with_lse  # noqa: F401
