"""TPU compute ops: the kernels under every model.

Plain-JAX reference implementations first (XLA fuses elementwise chains into
matmuls on its own); pallas kernels override the hot paths where XLA's
fusion isn't enough (flash attention, ring attention).
"""

from ray_tpu.ops.norms import rmsnorm  # noqa: F401
from ray_tpu.ops.rope import apply_rope, rope_angles  # noqa: F401
from ray_tpu.ops.attention import mha  # noqa: F401
