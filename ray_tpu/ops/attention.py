"""Multi-head attention with GQA, causal masking, and segment ids.

XLA-path implementation: one fused softmax(QK^T)V chain that the TPU backend
tiles onto the MXU. A pallas flash-attention kernel (``ops/pallas/flash.py``)
overrides this on real TPUs for long sequences; this einsum form is the
always-correct fallback and the numerics reference for the kernel tests.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -2.0**30  # large finite negative; avoids NaN from (-inf) - (-inf)


def mha(q: jax.Array, k: jax.Array, v: jax.Array, *,
        causal: bool = True,
        segment_ids: Optional[jax.Array] = None,
        bias: Optional[jax.Array] = None,
        scale: Optional[float] = None,
        q_offset: int = 0) -> jax.Array:
    """Attention over [batch, seq, heads, head_dim] tensors.

    Supports GQA: k/v may have fewer heads than q as long as
    ``q_heads % kv_heads == 0``. ``q_offset`` is the absolute position of
    q[0] relative to k (for decode with a KV cache). Softmax in fp32.
    """
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    scale = scale if scale is not None else d ** -0.5
    if hq != hkv:
        if hq % hkv:
            raise ValueError(f"q heads {hq} not divisible by kv heads {hkv}")
        group = hq // hkv
        q = q.reshape(b, sq, hkv, group, d)
        logits = jnp.einsum("bqhgd,bkhd->bhgqk", q * scale, k,
                            preferred_element_type=jnp.float32)
        logits = logits.reshape(b, hkv * group, sq, sk)
    else:
        logits = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k,
                            preferred_element_type=jnp.float32)

    mask = None
    if causal:
        qpos = jnp.arange(sq)[:, None] + q_offset
        kpos = jnp.arange(sk)[None, :]
        mask = qpos >= kpos  # [sq, sk]
        mask = mask[None, None, :, :]
    if segment_ids is not None:
        # [b, 1, sq, sk]; cross-segment attention is masked (packed sequences).
        seg_mask = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        mask = seg_mask if mask is None else (mask & seg_mask)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    if bias is not None:
        logits = logits + bias

    weights = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if hq != hkv:
        weights = weights.reshape(b, hkv, group, sq, sk)
        out = jnp.einsum("bhgqk,bkhd->bqhgd", weights, v)
        return out.reshape(b, sq, hq, d)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights, v)
    return out
