"""Rotary position embeddings."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_angles(seq_len: int, head_dim: int, theta: float = 10000.0,
                dtype=jnp.float32):
    """(sin, cos) tables of shape [seq_len, head_dim // 2]."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    pos = jnp.arange(seq_len, dtype=jnp.float32)
    angles = jnp.outer(pos, inv_freq)
    return jnp.sin(angles).astype(dtype), jnp.cos(angles).astype(dtype)


def apply_rope(x: jax.Array, sin: jax.Array, cos: jax.Array,
               positions: jax.Array | None = None) -> jax.Array:
    """Rotate pairs (x[..., ::2], x[..., 1::2]).

    x: [batch, seq, heads, head_dim]; sin/cos: [max_seq, head_dim//2] tables,
    gathered at ``positions`` ([batch, seq], defaults to arange) — the gather
    form supports decode-time offsets without retracing.
    """
    if positions is None:
        s = sin[: x.shape[1]][None, :, None, :]
        c = cos[: x.shape[1]][None, :, None, :]
    else:
        s = sin[positions][:, :, None, :]
        c = cos[positions][:, :, None, :]
    x1 = x[..., ::2]
    x2 = x[..., 1::2]
    rotated = jnp.stack([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    return rotated.reshape(x.shape).astype(x.dtype)
