"""Normalization ops."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rmsnorm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm in fp32 accumulation regardless of input dtype.

    The variance is computed in float32 (bf16 squares underflow), the scale
    applied in the input dtype so the op fuses into the adjacent matmul.
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * rms).astype(dtype) * weight
