"""DAG node types and the recursive executor.

Reference parity: ``python/ray/dag/dag_node.py`` (``DAGNode``),
``function_node.py``, ``class_node.py``, ``input_node.py``. Nodes capture a
remote call without submitting it; ``execute()`` walks the graph bottom-up,
submitting each node once and passing ObjectRefs downstream so the cluster
scheduler sees the whole graph's parallelism.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    """A lazily-evaluated node in a task/actor graph."""

    def __init__(self, args: Tuple, kwargs: Dict[str, Any]):
        self._bound_args = tuple(args)
        self._bound_kwargs = dict(kwargs)

    # -- graph traversal -----------------------------------------------------
    def _children(self) -> List["DAGNode"]:
        out: List[DAGNode] = []

        def scan(v):
            if isinstance(v, DAGNode):
                out.append(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    scan(x)
            elif isinstance(v, dict):
                for x in v.values():
                    scan(x)

        for a in self._bound_args:
            scan(a)
        for a in self._bound_kwargs.values():
            scan(a)
        return out

    def execute(self, *input_args, **input_kwargs):
        """Execute the whole DAG rooted at this node; returns the root's
        ObjectRef (or actor handle for a ClassNode root)."""
        cache: Dict[int, Any] = {}
        input_val = _InputValue(input_args, input_kwargs)
        return self._execute_node(cache, input_val)

    def _execute_node(self, cache: Dict[int, Any], input_val: "_InputValue"):
        key = id(self)
        if key not in cache:
            cache[key] = self._execute_impl(
                _resolve(self._bound_args, cache, input_val),
                _resolve(self._bound_kwargs, cache, input_val),
                input_val,
            )
        return cache[key]

    def _execute_impl(self, args, kwargs, input_val):
        raise NotImplementedError

    # -- introspection -------------------------------------------------------
    def get_all_nodes(self) -> List["DAGNode"]:
        seen: Dict[int, DAGNode] = {}

        def walk(n: DAGNode):
            if id(n) in seen:
                return
            seen[id(n)] = n
            for c in n._children():
                walk(c)

        walk(self)
        return list(seen.values())


class _InputValue:
    def __init__(self, args: Tuple, kwargs: Dict):
        self.args = args
        self.kwargs = kwargs

    def primary(self):
        if self.kwargs or len(self.args) > 1:
            raise ValueError(
                "DAG has a bare InputNode but execute() got multiple inputs; "
                "use InputNode attribute/index access in the DAG instead")
        return self.args[0] if self.args else None


def _resolve(value, cache, input_val):
    if isinstance(value, DAGNode):
        return value._execute_node(cache, input_val)
    if isinstance(value, tuple):
        return tuple(_resolve(v, cache, input_val) for v in value)
    if isinstance(value, list):
        return [_resolve(v, cache, input_val) for v in value]
    if isinstance(value, dict):
        return {k: _resolve(v, cache, input_val) for k, v in value.items()}
    return value


class FunctionNode(DAGNode):
    """``remote_fn.bind(...)`` — executes as ``remote_fn.remote(...)``."""

    def __init__(self, remote_fn, args, kwargs, options: Optional[Dict] = None):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn
        self._options = options or {}

    def options(self, **opts) -> "FunctionNode":
        merged = dict(self._options)
        merged.update(opts)
        return FunctionNode(self._remote_fn, self._bound_args,
                            self._bound_kwargs, merged)

    def _execute_impl(self, args, kwargs, input_val):
        fn = self._remote_fn
        if self._options:
            fn = fn.options(**self._options)
        return fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """``ActorClass.bind(...)`` — executes by creating the actor once."""

    def __init__(self, actor_cls, args, kwargs, options: Optional[Dict] = None):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls
        self._options = options or {}

    def options(self, **opts) -> "ClassNode":
        merged = dict(self._options)
        merged.update(opts)
        return ClassNode(self._actor_cls, self._bound_args,
                         self._bound_kwargs, merged)

    def __getattr__(self, name: str) -> "_BoundMethod":
        if name.startswith("_"):
            raise AttributeError(name)
        return _BoundMethod(self, name)

    def _execute_impl(self, args, kwargs, input_val):
        cls = self._actor_cls
        if self._options:
            cls = cls.options(**self._options)
        return cls.remote(*args, **kwargs)


class _BoundMethod:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method_name, args, kwargs)


class ClassMethodNode(DAGNode):
    """``class_node.method.bind(...)`` — actor method call on the (shared)
    actor created by the parent ClassNode. The parent may also be a live
    ActorHandle (``handle.method.bind(...)``), in which case no actor is
    created at execute time."""

    def __init__(self, class_node, method_name: str, args, kwargs):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method_name = method_name

    def _children(self) -> List[DAGNode]:
        base = super()._children()
        if isinstance(self._class_node, DAGNode):
            return [self._class_node] + base
        return base

    def _execute_impl(self, args, kwargs, input_val):
        raise AssertionError("handled in _execute_node")

    def _execute_node(self, cache, input_val):
        key = id(self)
        if key not in cache:
            if isinstance(self._class_node, DAGNode):
                handle = self._class_node._execute_node(cache, input_val)
            else:
                handle = self._class_node  # live ActorHandle
            args = _resolve(self._bound_args, cache, input_val)
            kwargs = _resolve(self._bound_kwargs, cache, input_val)
            cache[key] = getattr(handle, self._method_name).remote(*args, **kwargs)
        return cache[key]


class InputNode(DAGNode):
    """Placeholder for the value passed to ``dag.execute(x)``.

    Context-manager form matches the reference API::

        with InputNode() as inp:
            dag = f.bind(inp)
        dag.execute(41)
    """

    def __init__(self):
        super().__init__((), {})

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        pass

    def __getattr__(self, name: str) -> "InputAttributeNode":
        if name.startswith("_"):
            raise AttributeError(name)
        return InputAttributeNode(self, name, kind="attr")

    def __getitem__(self, key) -> "InputAttributeNode":
        return InputAttributeNode(self, key, kind="item")

    def _execute_impl(self, args, kwargs, input_val):
        return input_val.primary()


class InputAttributeNode(DAGNode):
    """``inp.x`` / ``inp[0]`` — keyword or positional slice of execute()'s
    inputs: ``inp[i]`` is the i-th positional arg, ``inp.name`` the kwarg."""

    def __init__(self, input_node: InputNode, key, kind: str):
        super().__init__((), {})
        self._input_node = input_node
        self._key = key
        self._kind = kind

    def _children(self) -> List[DAGNode]:
        return []

    def _execute_impl(self, args, kwargs, input_val):
        if self._kind == "item":
            if isinstance(self._key, int):
                return input_val.args[self._key]
            return input_val.kwargs[self._key]
        return input_val.kwargs[self._key]
