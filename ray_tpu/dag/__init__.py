"""Lazy task/actor DAGs: ``fn.bind(...)`` builds a graph executed on demand.

Capability parity with the reference's ``python/ray/dag/`` (``DAGNode`` in
``dag/dag_node.py``; ``FunctionNode``/``ClassNode`` built by ``.bind()``;
``InputNode`` placeholder). Used by the serve layer for model composition and
by the workflow layer for durable execution.
"""

from ray_tpu.dag.dag_node import (  # noqa: F401
    ClassMethodNode,
    ClassNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
)

__all__ = [
    "DAGNode", "FunctionNode", "ClassNode", "ClassMethodNode", "InputNode",
    "InputAttributeNode",
]
