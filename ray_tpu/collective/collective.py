"""Host-plane collectives between tasks/actors via a rendezvous actor.

API shape mirrors the reference's ``ray.util.collective.collective``: members
join a named group with (world_size, rank), then issue symmetric collective
calls in program order. The group actor synchronizes round n across all
ranks (every rank's n-th call is matched — the same program-order contract
NCCL imposes).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

_REDUCE_OPS = {
    "sum": lambda arrs: _tree_reduce(arrs, np.add),
    "prod": lambda arrs: _tree_reduce(arrs, np.multiply),
    "min": lambda arrs: _tree_reduce(arrs, np.minimum),
    "max": lambda arrs: _tree_reduce(arrs, np.maximum),
}


def _tree_reduce(arrs: List[Any], op) -> Any:
    acc = arrs[0]
    for a in arrs[1:]:
        acc = op(acc, a)
    return acc


class _CollectiveGroupActor:
    """Async rendezvous actor: one instance per group (max_concurrency high
    so every rank can block in the same round concurrently)."""

    def __init__(self, world_size: int):
        import asyncio

        self.world_size = world_size
        self._rounds: Dict[int, Dict] = {}
        self._lock = asyncio.Lock()

    async def op(self, seq: int, rank: int, opname: str, payload, meta):
        import asyncio

        async with self._lock:
            rnd = self._rounds.get(seq)
            if rnd is None:
                rnd = {"data": {}, "meta": {}, "event": asyncio.Event(),
                       "result": None}
                self._rounds[seq] = rnd
            rnd["data"][rank] = payload
            rnd["meta"][rank] = meta
            complete = len(rnd["data"]) == self.world_size
            if complete:
                rnd["result"] = self._finish(opname, rnd)
                rnd["event"].set()
        if not complete:
            await rnd["event"].wait()
        result = rnd["result"]
        async with self._lock:
            rnd["meta"].setdefault("_done", set()).add(rank)
            if len(rnd["meta"]["_done"]) == self.world_size:
                self._rounds.pop(seq, None)
        if opname in ("allgather",):
            return result
        if opname in ("reducescatter",):
            return result[rank]
        return result

    def _finish(self, opname: str, rnd: Dict):
        data = [rnd["data"][r] for r in range(self.world_size)]
        if opname == "barrier":
            return None
        if opname == "allreduce":
            reduce_op = rnd["meta"][0]["op"]
            return _REDUCE_OPS[reduce_op](data)
        if opname == "broadcast":
            src = rnd["meta"][0]["src"]
            return rnd["data"][src]
        if opname == "allgather":
            return data
        if opname == "reducescatter":
            reduce_op = rnd["meta"][0]["op"]
            reduced = _REDUCE_OPS[reduce_op](data)
            return np.array_split(reduced, self.world_size)
        raise ValueError(f"unknown collective {opname!r}")


class _GroupHandle:
    def __init__(self, name: str, world_size: int, rank: int, actor):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.actor = actor
        self.seq = 0


_local = threading.local()


def _groups() -> Dict[str, _GroupHandle]:
    if not hasattr(_local, "groups"):
        _local.groups = {}
    return _local.groups


_NAMESPACE = "_rt_collective"


def create_collective_group(world_size: int, group_name: str = "default") -> None:
    """Declare the group (idempotent); members still call init_*."""
    import ray_tpu

    ray_tpu.remote(max_concurrency=max(world_size * 2, 8))(
        _CollectiveGroupActor).options(
        name=f"cg:{group_name}", namespace=_NAMESPACE,
        get_if_exists=True, lifetime="detached").remote(world_size)


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> None:
    import ray_tpu

    if not (0 <= rank < world_size):
        raise ValueError(f"rank {rank} out of range for world_size {world_size}")
    create_collective_group(world_size, group_name)
    actor = ray_tpu.get_actor(f"cg:{group_name}", namespace=_NAMESPACE)
    _groups()[group_name] = _GroupHandle(group_name, world_size, rank, actor)


def _handle(group_name: str) -> _GroupHandle:
    h = _groups().get(group_name)
    if h is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized in this "
            f"worker; call init_collective_group(world_size, rank) first")
    return h


def _call(group_name: str, opname: str, payload, meta) -> Any:
    import ray_tpu

    h = _handle(group_name)
    seq = h.seq
    h.seq += 1
    return ray_tpu.get(h.actor.op.remote(seq, h.rank, opname, payload, meta))


def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    return _call(group_name, "allreduce", np.asarray(tensor), {"op": op})


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _call(group_name, "broadcast", np.asarray(tensor), {"src": src_rank})


def allgather(tensor, group_name: str = "default") -> List:
    return _call(group_name, "allgather", np.asarray(tensor), {})


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    return _call(group_name, "reducescatter", np.asarray(tensor), {"op": op})


def barrier(group_name: str = "default") -> None:
    _call(group_name, "barrier", None, {})


def destroy_collective_group(group_name: str = "default") -> None:
    import ray_tpu

    _groups().pop(group_name, None)
    try:
        actor = ray_tpu.get_actor(f"cg:{group_name}", namespace=_NAMESPACE)
        ray_tpu.kill(actor)
    except ValueError:
        pass
