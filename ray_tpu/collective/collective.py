"""Host-plane collectives with a peer-to-peer tensor path.

API shape mirrors the reference's ``ray.util.collective.collective``
(``collective.py:120-621``): members join a named group with (world_size,
rank), then issue symmetric collective calls in program order, plus
point-to-point ``send``/``recv`` (``collective.py:531-621``).

Redesign of the data plane: the named rendezvous actor holds ONLY membership
(rank -> RPC address) and runs barriers — tensor bytes never pass through it
(the reference keeps payloads out of the store the same way: NCCL moves them
directly between ranks). Payloads travel over direct worker-to-worker RPC
into per-(group, src) FIFO mailboxes; allreduce/reducescatter/allgather run
as ring algorithms over those links, so per-op traffic is O(bytes) per link
rather than O(world * bytes) through one actor.

Correctness of message matching relies on the same contract NCCL imposes:
each rank issues group ops in identical program order, and each (src -> dst)
link delivers FIFO (single pooled connection, ordered writes).
"""

from __future__ import annotations

import asyncio
import itertools
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_REDUCE_NP = {
    "sum": np.add,
    "prod": np.multiply,
    "min": np.minimum,
    "max": np.maximum,
}


class _CollectiveGroupActor:
    """Rendezvous actor: membership + barrier. CONTROL PLANE ONLY — no
    method accepts tensor payloads; ``stats()`` proves it to tests."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.members: Dict[int, str] = {}
        self._member_event = asyncio.Event()
        self._barriers: Dict[int, Dict] = {}
        self._register_calls = 0

    async def register(self, rank: int, address: str) -> Dict[int, str]:
        self._register_calls += 1
        self.members[rank] = address
        if len(self.members) == self.world_size:
            self._member_event.set()
        await self._member_event.wait()
        return dict(self.members)

    async def barrier_op(self, seq: int, rank: int) -> None:
        rnd = self._barriers.get(seq)
        if rnd is None:
            rnd = self._barriers[seq] = {"arrived": set(),
                                         "event": asyncio.Event()}
        rnd["arrived"].add(rank)
        if len(rnd["arrived"]) == self.world_size:
            rnd["event"].set()
            self._barriers.pop(seq, None)
        await rnd["event"].wait()

    async def stats(self) -> Dict[str, int]:
        return {"register_calls": self._register_calls,
                "payload_bytes": 0}


class _Mailboxes:
    """Per-process (group, src, dst, channel) -> FIFO of payloads.

    ``dst`` keeps multi-member processes (local mode) from cross-delivering;
    ``channel`` separates ring-collective traffic from p2p send/recv so a
    buffered early ``send`` can never be consumed by a later collective's
    ring step (both are FIFO within a channel)."""

    def __init__(self):
        self._boxes: Dict[Tuple, deque] = {}
        self._waiters: Dict[Tuple, deque] = {}

    def deliver(self, key: Tuple, payload) -> None:
        waiters = self._waiters.get(key)
        while waiters:
            fut = waiters.popleft()
            if not fut.done():
                fut.set_result(payload)
                return
        self._boxes.setdefault(key, deque()).append(payload)

    async def take(self, key: Tuple):
        box = self._boxes.get(key)
        if box:
            return box.popleft()
        fut = asyncio.get_running_loop().create_future()
        self._waiters.setdefault(key, deque()).append(fut)
        return await fut


class _RpcPlane:
    """P2P plane for the cluster backend: mailbox service registered on the
    worker's existing RpcServer; sends ride the shared connection pool."""

    def __init__(self, backend):
        self.backend = backend
        self.address = backend.address
        self.mail = _Mailboxes()
        backend.server.register("coll_send", self._rpc_coll_send)

    async def _rpc_coll_send(self, p):
        self.mail.deliver((p["group"], p["src"], p["dst"], p["ch"]),
                          p["payload"])
        return {"ok": True}

    async def send_async(self, dst_addr: str, group: str, src: int, dst: int,
                         payload, ch: str = "ring") -> None:
        if dst_addr == self.address:
            self.mail.deliver((group, src, dst, ch), payload)
            return
        client = await self.backend._pool.get(dst_addr)
        await client.call("coll_send", {"group": group, "src": src,
                                        "dst": dst, "ch": ch,
                                        "payload": payload})

    async def recv_async(self, group: str, src: int, dst: int,
                         ch: str = "ring"):
        return await self.mail.take((group, src, dst, ch))

    def run(self, coro):
        return self.backend.io.run(coro)


class _ThreadPlane:
    """Local-mode plane: members are threads of one process sharing a single
    background loop; 'addresses' are rank markers, delivery is in-memory."""

    _shared = None
    _shared_lock = threading.Lock()

    def __init__(self):
        from ray_tpu.cluster.rpc import EventLoopThread

        self.io = EventLoopThread(name="rt-collective-local")
        self.mail = _Mailboxes()
        self.address = "local"

    @classmethod
    def shared(cls) -> "_ThreadPlane":
        with cls._shared_lock:
            if cls._shared is None or not cls._shared.io._thread.is_alive():
                cls._shared = cls()
            return cls._shared

    async def send_async(self, dst_addr: str, group: str, src: int, dst: int,
                         payload, ch: str = "ring") -> None:
        self.mail.deliver((group, src, dst, ch), payload)

    async def recv_async(self, group: str, src: int, dst: int,
                         ch: str = "ring"):
        return await self.mail.take((group, src, dst, ch))

    def run(self, coro):
        return self.io.run(coro)


class _GroupHandle:
    def __init__(self, name: str, world_size: int, rank: int, actor,
                 plane, members: Dict[int, str]):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.actor = actor
        self.plane = plane
        self.members = members
        self.barrier_seq = 0


_local = threading.local()


def _groups() -> Dict[str, _GroupHandle]:
    if not hasattr(_local, "groups"):
        _local.groups = {}
    return _local.groups


_NAMESPACE = "_rt_collective"


def create_collective_group(world_size: int, group_name: str = "default") -> None:
    """Declare the group (idempotent); members still call init_*."""
    import ray_tpu

    ray_tpu.remote(max_concurrency=max(world_size * 2, 8))(
        _CollectiveGroupActor).options(
        name=f"cg:{group_name}", namespace=_NAMESPACE,
        get_if_exists=True, lifetime="detached").remote(world_size)


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> None:
    import ray_tpu
    from ray_tpu.core.worker import global_worker

    if not (0 <= rank < world_size):
        raise ValueError(f"rank {rank} out of range for world_size {world_size}")
    create_collective_group(world_size, group_name)
    actor = ray_tpu.get_actor(f"cg:{group_name}", namespace=_NAMESPACE)

    backend = global_worker()._require_backend()
    if hasattr(backend, "server") and hasattr(backend, "io"):
        plane = getattr(backend, "_collective_plane", None)
        if plane is None:
            plane = backend._collective_plane = _RpcPlane(backend)
        my_addr = plane.address
    else:  # local/threaded backend: in-process delivery
        plane = _ThreadPlane.shared()
        my_addr = f"local:{rank}"
    members = ray_tpu.get(actor.register.remote(rank, my_addr))
    _groups()[group_name] = _GroupHandle(group_name, world_size, rank, actor,
                                         plane, members)


def _handle(group_name: str) -> _GroupHandle:
    h = _groups().get(group_name)
    if h is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized in this "
            f"worker; call init_collective_group(world_size, rank) first")
    return h


# ---- ring algorithms (generic over the plane's async send/recv) -------------

async def _ring_reduce_scatter(h: _GroupHandle, chunks: List[np.ndarray],
                               op) -> int:
    """In-place ring reduce-scatter; returns the index this rank owns
    (fully reduced) at the end: (rank + 1) % W."""
    W, rank = h.world_size, h.rank
    right_rank = (rank + 1) % W
    right = h.members[right_rank]
    left = (rank - 1) % W
    for step in range(W - 1):
        send_idx = (rank - step) % W
        recv_idx = (rank - step - 1) % W
        send_fut = asyncio.ensure_future(
            h.plane.send_async(right, h.name, rank, right_rank,
                               chunks[send_idx]))
        incoming = await h.plane.recv_async(h.name, left, rank)
        chunks[recv_idx] = op(chunks[recv_idx], incoming)
        await send_fut
    return (rank + 1) % W


async def _ring_allgather_chunks(h: _GroupHandle, chunks: List,
                                 owned_idx: int) -> None:
    """Ring allgather: every rank starts owning chunks[owned_idx]; after
    W-1 steps all entries are filled."""
    W, rank = h.world_size, h.rank
    right_rank = (rank + 1) % W
    right = h.members[right_rank]
    left = (rank - 1) % W
    for step in range(W - 1):
        send_idx = (owned_idx - step) % W
        recv_idx = (owned_idx - step - 1) % W
        send_fut = asyncio.ensure_future(
            h.plane.send_async(right, h.name, rank, right_rank,
                               chunks[send_idx]))
        chunks[recv_idx] = await h.plane.recv_async(h.name, left, rank)
        await send_fut


def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    h = _handle(group_name)
    arr = np.asarray(tensor)
    npop = _REDUCE_NP[op]
    if h.world_size == 1:
        return arr.copy()
    flat = arr.ravel()
    chunks = [c.copy() for c in np.array_split(flat, h.world_size)]

    async def _run():
        owned = await _ring_reduce_scatter(h, chunks, npop)
        await _ring_allgather_chunks(h, chunks, owned)
        return chunks

    out = h.plane.run(_run())
    return np.concatenate(out).reshape(arr.shape)


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    h = _handle(group_name)
    arr = np.asarray(tensor)
    npop = _REDUCE_NP[op]
    if h.world_size == 1:
        return arr.copy()
    chunks = [c.copy() for c in np.array_split(arr, h.world_size)]

    async def _run():
        owned = await _ring_reduce_scatter(h, chunks, npop)
        # each rank ends owning chunk (rank+1)%W; one neighbor hop routes
        # every chunk to its home rank
        owner = h.members[owned]
        me = h.rank
        if owned == me:
            return chunks[owned]
        send_fut = asyncio.ensure_future(
            h.plane.send_async(owner, h.name, me, owned, chunks[owned]))
        result = await h.plane.recv_async(h.name, (me - 1) % h.world_size, me)
        await send_fut
        return result

    return h.plane.run(_run())


def allgather(tensor, group_name: str = "default") -> List:
    h = _handle(group_name)
    arr = np.asarray(tensor)
    if h.world_size == 1:
        return [arr.copy()]
    parts: List[Optional[np.ndarray]] = [None] * h.world_size
    parts[h.rank] = arr

    async def _run():
        await _ring_allgather_chunks(h, parts, h.rank)
        return parts

    return h.plane.run(_run())


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    h = _handle(group_name)
    arr = np.asarray(tensor)
    if h.world_size == 1:
        return arr.copy()

    async def _run():
        if h.rank == src_rank:
            await asyncio.gather(*[
                h.plane.send_async(h.members[r], h.name, h.rank, r, arr)
                for r in range(h.world_size) if r != src_rank])
            return arr
        return await h.plane.recv_async(h.name, src_rank, h.rank)

    return h.plane.run(_run())


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    """Point-to-point send (reference: ``collective.py:531``). Buffered:
    completes once the payload is in the receiver's mailbox — the matching
    ``recv`` may run later."""
    h = _handle(group_name)
    if dst_rank == h.rank:
        raise ValueError("cannot send to self")
    arr = np.asarray(tensor)
    h.plane.run(
        h.plane.send_async(h.members[dst_rank], h.name, h.rank, dst_rank,
                           arr, ch="p2p"))


def recv(tensor, src_rank: int, group_name: str = "default"):
    """Point-to-point receive into ``tensor`` (in place when possible,
    reference: ``collective.py:594``); also returns the received array."""
    h = _handle(group_name)
    if src_rank == h.rank:
        raise ValueError("cannot recv from self")
    got = h.plane.run(
        h.plane.recv_async(h.name, src_rank, h.rank, ch="p2p"))
    target = np.asarray(tensor)
    if target.flags.writeable:
        np.copyto(target, got)  # shape/dtype mismatch raises — no silent drop
    return got


def barrier(group_name: str = "default") -> None:
    import ray_tpu

    h = _handle(group_name)
    seq = h.barrier_seq
    h.barrier_seq += 1
    ray_tpu.get(h.actor.barrier_op.remote(seq, h.rank))


def group_stats(group_name: str = "default") -> Dict[str, int]:
    """Rendezvous-actor traffic counters (control plane only — tests assert
    payload_bytes stays 0)."""
    import ray_tpu

    h = _handle(group_name)
    return ray_tpu.get(h.actor.stats.remote())


# ---------------------------------------------------------------------------
# Weight shipping over the push-stream object plane (RLHF weight sync)
# ---------------------------------------------------------------------------
#
# ``ship_params`` / ``fetch_params`` move one parameter pytree between two
# processes over ``cluster/stream.py``: the producer registers the
# shipment as a stream source (meta frame + one frame per leaf — large
# leaves spill to plasma and travel as oid references, so a same-node
# consumer mmaps them zero-copy and the bytes land on the
# ``rt_stream_*`` series); the consumer subscribes and drains one-way
# push frames. When the channel breaks mid-shipment (reconnect, chaos
# ``rpc.drop``), the consumer falls back to ONE ``coll_param_reclaim``
# RPC that replays the undelivered tail from the producer's replay
# buffer and drains the rest of the pump — leaf-exact across the
# transport switch, the same contract the serve stream fallback keeps.

_PARAM_RPC = "coll_param_reclaim"

_ship_lock = threading.Lock()
_ship_ids = itertools.count()  # rt: guarded-by(_ship_lock)

#: producer-side transfer receipts, keyed by sid — the pump stamps its
#: first/last ``take`` so the RLHF flight recorder can join the pump
#: wall with the consumer's fetch wall and the engine's swap barrier
_receipts: "OrderedDict[str, Dict[str, Any]]" = \
    OrderedDict()  # rt: guarded-by(_ship_lock)


class _ParamsPump:
    """Finite list pump for one shipment (the stream-source contract).
    Stamps its receipt on every ``take`` — both the push path and the
    reclaim fallback drain through here, so the pump wall is
    transport-agnostic."""

    def __init__(self, items: List[Any],
                 receipt: Optional[Dict[str, Any]] = None):
        self._items = list(items)
        self._pos = 0
        self._receipt = receipt

    async def take(self, n: int) -> Tuple[List[Any], bool]:
        out = self._items[self._pos:self._pos + n]
        self._pos += len(out)
        done = self._pos >= len(self._items)
        if self._receipt is not None and out:
            now = time.time()
            with _ship_lock:
                self._receipt.setdefault("t_pump0", now)
                self._receipt["t_pump1"] = now
                self._receipt["frames_taken"] = \
                    self._receipt.get("frames_taken", 0) + len(out)
                if done:
                    self._receipt["pump_done"] = True
        return out, done

    def close(self) -> None:
        self._items = []


def shipment_receipt(sid: str) -> Optional[Dict[str, Any]]:
    """Producer-side transfer receipt for one shipment: frames pumped
    and the pump wall (first ``take`` to last ``take``). Survives the
    shipment's deregistration so the driver can read it AFTER the
    consumer redeemed the ticket; the registry keeps the last 32."""
    with _ship_lock:
        r = _receipts.get(sid)
        if r is None:
            return None
        out = dict(r)
    if "t_pump0" in out and "t_pump1" in out:
        out["pump_wall_s"] = round(out["t_pump1"] - out["t_pump0"], 6)
    return out


def _params_backend():
    from ray_tpu.core.worker import global_worker

    backend = global_worker()._require_backend()
    if not (hasattr(backend, "server") and hasattr(backend, "io")):
        raise RuntimeError(
            "ship_params/fetch_params need the cluster backend (a real "
            "ray_tpu.init() session; the threaded local backend has no "
            "stream transport)")
    return backend


def _ensure_reclaim_rpc(backend) -> None:
    with _ship_lock:
        if getattr(backend, "_rt_param_reclaim", False):
            return

        async def _rpc(p):
            return await _reclaim_shipment(p["sid"], int(p["delivered"]))

        backend.server.register(_PARAM_RPC, _rpc)
        backend._rt_param_reclaim = True


async def _reclaim_shipment(sid: str, delivered: int) -> Dict[str, Any]:
    """Producer-side pull fallback: replay the pushed-but-undelivered
    tail, then drain the rest of the pump (shipments are finite, so one
    reply completes the stream). Runs on the producer's event loop."""
    from ray_tpu.cluster import stream as rt_stream

    items, known, err = await rt_stream.drain_source(sid, delivered)
    if err is not None:
        return {"error": repr(err)}
    if not known:
        return {"error": f"shipment {sid!r} unknown "
                         f"(already fetched or cancelled)"}
    return {"items": items, "done": True}


def ship_params(params: Any, *, sid: Optional[str] = None) -> Dict[str, Any]:
    """Register one parameter pytree for streaming to a consumer.

    Returns the shipment TICKET — ``{"address", "sid", "n_leaves",
    "nbytes"}`` — which the caller hands to the consumer (an actor-call
    argument); the consumer redeems it with :func:`fetch_params`. The
    tensor bytes never ride the actor call: they travel as push-stream
    frames (plasma oid references above the inline threshold) when the
    consumer subscribes.

    One ticket is redeemable ONCE — the shipment deregisters when the
    consumer completes it (push or fallback). Ship again for each sync
    round; an unredeemed shipment is dropped with
    :func:`cancel_shipment`.
    """
    import jax

    from ray_tpu.cluster import stream as rt_stream

    backend = _params_backend()
    _ensure_reclaim_rpc(backend)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    np_leaves = [np.asarray(leaf) for leaf in leaves]
    nbytes = int(sum(leaf.nbytes for leaf in np_leaves))
    if sid is None:
        with _ship_lock:
            sid = f"params-{os.getpid()}-{next(_ship_ids)}"
    meta = {"treedef": treedef, "n_leaves": len(np_leaves),
            "nbytes": nbytes}
    receipt = {"sid": sid, "t_ship": time.time(), "nbytes": nbytes,
               "n_leaves": len(np_leaves)}
    with _ship_lock:
        _receipts[sid] = receipt
        while len(_receipts) > 32:  # bound the receipt registry
            _receipts.popitem(last=False)
    rt_stream.register_source(sid, _ParamsPump([meta] + np_leaves,
                                               receipt=receipt))
    return {"address": backend.address, "sid": sid,
            "n_leaves": len(np_leaves), "nbytes": nbytes}


def cancel_shipment(ticket: Dict[str, Any]) -> None:
    """Drop an unredeemed shipment (producer side)."""
    from ray_tpu.cluster import stream as rt_stream

    rt_stream.unregister_source(ticket["sid"])


async def _fetch_async(backend, address: str, sid: str,
                       window: Optional[int]) -> Tuple[List[Any], str, int]:
    from ray_tpu.cluster import stream as rt_stream
    from ray_tpu.cluster.rpc import ChannelBroken

    items: List[Any] = []
    transport = "push"
    rpcs = 1  # the subscribe (or the reclaim, on the no-push path)
    ch = None
    done = False
    try:
        ch = await rt_stream.subscribe(backend, address, sid, window)
    except Exception:  # noqa: BLE001 — no push service: pull instead
        ch = None
    if ch is None:
        transport = "pull"
    else:
        try:
            while True:
                item, d = await rt_stream.take_decoded(backend, ch)
                if d:
                    done = True
                    break
                items.append(item)
        except ChannelBroken:
            # undecoded frames still parked in the channel are DISCARDED
            # here — the producer's replay buffer holds every unacked
            # item, and the reclaim below filters by our delivered count
            transport = "fallback"
    if not done:
        if ch is not None:
            ch.close()
            ch = None
        client = await backend._pool.get(address)
        reply = await client.call(
            _PARAM_RPC, {"sid": sid, "delivered": len(items)},
            timeout=120.0)
        rpcs += 1
        if reply.get("error"):
            raise RuntimeError(f"param shipment {sid!r} failed: "
                               f"{reply['error']}")
        items.extend(reply["items"])
    if ch is not None:
        ch.close()
    return items, transport, rpcs


def fetch_params(ticket: Dict[str, Any], *,
                 window: Optional[int] = None
                 ) -> Tuple[Any, Dict[str, Any]]:
    """Redeem a :func:`ship_params` ticket: subscribe to the producer's
    shipment stream, drain it (push frames; oid frames resolve through
    the object plane — same-node zero-copy), rebuild the pytree.
    Falls back to the one-RPC reclaim path on a broken channel,
    leaf-exact. Returns ``(params, info)`` where info carries
    ``transport`` (push / fallback / pull), ``rpcs`` and ``nbytes``."""
    import jax

    from ray_tpu.cluster import stream as rt_stream

    backend = _params_backend()
    t_fetch0 = time.perf_counter()
    items, transport, rpcs = backend.io.run(
        _fetch_async(backend, ticket["address"], ticket["sid"], window))
    fetch_wall_s = time.perf_counter() - t_fetch0
    try:
        rt_stream.observe_request_rpcs(transport, rpcs)
    except Exception:  # noqa: BLE001 — telemetry never fails the fetch
        pass
    if not items or not isinstance(items[0], dict) \
            or "treedef" not in items[0]:
        raise RuntimeError(
            f"param shipment {ticket['sid']!r}: missing meta frame")
    meta, leaves = items[0], items[1:]
    if len(leaves) != meta["n_leaves"]:
        raise RuntimeError(
            f"param shipment {ticket['sid']!r}: {len(leaves)} leaves "
            f"arrived, expected {meta['n_leaves']} (transport drop?)")
    params = jax.tree_util.tree_unflatten(meta["treedef"], leaves)
    # leaves above the inline threshold travelled as plasma oid frames
    # on the push path (deterministic encode rule — see _PushBinding.
    # _encode): report the count so benches/tests can assert the object
    # plane was actually exercised
    thresh = rt_stream.inline_max_bytes()
    oid_leaves = sum(1 for leaf in leaves
                     if getattr(leaf, "nbytes", 0) > thresh)
    return params, {"transport": transport, "rpcs": rpcs,
                    "nbytes": meta["nbytes"],
                    "n_leaves": meta["n_leaves"],
                    "oid_leaves": oid_leaves,
                    "inline_leaves": meta["n_leaves"] - oid_leaves,
                    "fetch_wall_s": round(fetch_wall_s, 6)}


def destroy_collective_group(group_name: str = "default") -> None:
    import ray_tpu

    _groups().pop(group_name, None)
    try:
        actor = ray_tpu.get_actor(f"cg:{group_name}", namespace=_NAMESPACE)
        ray_tpu.kill(actor)
    except ValueError:
        pass
