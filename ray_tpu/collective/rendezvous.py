"""jax.distributed bootstrap through the GCS KV.

The TPU replacement for the reference's NCCL process-group setup
(``train/torch/config.py:64`` — rank-0 TCP rendezvous + env vars): rank 0
publishes its coordinator address under a KV key; other ranks poll the key;
then every rank calls ``jax.distributed.initialize`` and XLA's collectives
see the full multi-host device set. The KV plays the role the named
rendezvous actor plays for NCCL unique ids in the reference
(``collective_group/nccl_util.py``).
"""

from __future__ import annotations

import os
import socket
import time
from typing import Optional


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _kv_key(group_name: str) -> str:
    return f"@rendezvous/{group_name}/coordinator"


def _publish_or_await_coordinator(backend, key: str, rank: int,
                                  coordinator_ip: Optional[str],
                                  timeout_s: float, what: str) -> str:
    """Rank 0 publishes ip:port under ``key``; other ranks poll it.
    The one rendezvous used by both the jax and torch bootstraps."""
    if rank == 0:
        ip = coordinator_ip or socket.gethostbyname(socket.gethostname())
        address = f"{ip}:{_free_port()}"
        backend.kv_put(key, address.encode())
        return address
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        raw = backend.kv_get(key)
        if raw:
            return raw.decode()
        time.sleep(0.1)
    raise TimeoutError(
        f"{what}: coordinator address not published within {timeout_s}s")


def bootstrap_jax_distributed(world_size: int, rank: int,
                              group_name: str = "train",
                              coordinator_ip: Optional[str] = None,
                              timeout_s: float = 60.0,
                              local_device_ids=None,
                              instance_token: Optional[str] = None) -> None:
    """Call from every member of a gang (one process per host).

    Single-process gangs (world_size == 1) skip distributed init entirely —
    jax sees its local devices and meshes work unchanged.

    ``instance_token``, when given, namespaces the rendezvous key so a rank
    can never pick up the coordinator address a *previous* gang with the
    same group_name left in the KV. Callers may equivalently bake a fresh
    uuid into ``group_name`` itself — that is what ``JaxTrainer`` does
    (``train/trainer.py`` generates a per-restart group name), so the token
    is the explicit form of the same convention. Without either, the key is
    deleted after a successful init (rank 0, once every rank has connected)
    to keep sequential reuse of the default name safe.
    """
    import ray_tpu
    from ray_tpu.core.worker import global_worker

    if world_size <= 1:
        return
    backend = global_worker()._require_backend()
    key = _kv_key(group_name if instance_token is None
                  else f"{group_name}/{instance_token}")
    try:
        address = _publish_or_await_coordinator(
            backend, key, rank, coordinator_ip, timeout_s,
            f"rendezvous {group_name!r}")
    except TimeoutError as e:
        # CPU-graceful covers the await too: when a CPU gang's rank 0
        # degraded (and cleaned its key), the peers must degrade with it
        # rather than die on the missing coordinator
        if _rendezvous_strict() or not _cpu_only_backend():
            raise
        import logging

        logging.getLogger("ray_tpu.rendezvous").warning(
            "rendezvous for %r timed out on a CPU-only host (%s); rank %d "
            "continues with local jax", group_name, e, rank)
        # "local jax" must actually be local: a pooled worker may still
        # hold the PREVIOUS gang's coordinator client (see the teardown
        # note below) — shut it down on this degrade path too
        _shutdown_previous_gang()
        return
    import jax

    _shutdown_previous_gang()

    try:  # jax 0.4.x gates CPU cross-process collectives behind gloo opt-in
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except AttributeError:  # newer jax: on by default, option removed
        pass
    kwargs = dict(coordinator_address=address,
                  num_processes=world_size,
                  process_id=rank,
                  local_device_ids=local_device_ids)
    try:
        try:
            # bound the rendezvous where jax supports it: a gang member
            # that died pre-connect must fail THIS rank loudly in
            # timeout_s, not hang the whole gang on a default 5-minute wait
            jax.distributed.initialize(
                initialization_timeout=max(1, int(timeout_s)), **kwargs)
        except TypeError:  # older jax: no initialization_timeout kwarg
            jax.distributed.initialize(**kwargs)
    except Exception as e:  # noqa: BLE001
        # CPU-graceful: on a CPU-only host a failed process-group bootstrap
        # degrades to local (un-distributed) jax — the gang still runs, each
        # rank seeing its own devices — so the multi-host product path can
        # be exercised (and chaos-tested) without TPUs. On real accelerator
        # hosts, or with RT_RENDEZVOUS_STRICT=1, the failure is fatal: a
        # silent single-host fallback there would train the wrong program.
        if _rendezvous_strict() or not _cpu_only_backend():
            raise
        import logging

        logging.getLogger("ray_tpu.rendezvous").warning(
            "jax.distributed bootstrap for %r failed on a CPU-only host "
            "(%s: %s); rank %d continues with local jax "
            "(set RT_RENDEZVOUS_STRICT=1 to make this fatal)",
            group_name, type(e).__name__, e, rank)
        if rank == 0:
            # clean the rendezvous key on the degrade path too — a stale
            # coordinator address must not greet the next gang reusing
            # this group_name (peers that miss it degrade the same way
            # via the await-timeout branch above)
            try:
                backend.kv_del(key)
            except Exception:  # noqa: BLE001
                pass
        return
    if rank == 0:
        # initialize() returns only after every process connected, so all
        # ranks have read the key — safe to clear it now.
        try:
            backend.kv_del(key)
        except Exception:
            pass


def _shutdown_previous_gang() -> None:
    """Elastic-restart lifecycle (SURVEY.md §7 hard part: "jax.distributed
    lifecycle across actor restarts"): a pooled/reused worker process may
    carry a previous gang's coordinator client whose peers are gone — tear
    it down and drop cached backends so the new device topology can
    register (or so a degraded rank truly runs LOCAL jax). NCCL's
    equivalent is destroy_process_group before re-init. getattr guard:
    very old jax builds predate is_initialized — treat them as
    never-initialized instead of dying before the bootstrap."""
    import jax

    if getattr(jax.distributed, "is_initialized", lambda: False)():
        try:
            jax.distributed.shutdown()
        except Exception:  # noqa: BLE001
            # The old gang's coordinator may already be dead (that's often
            # WHY we're re-bootstrapping) — a failed goodbye to it must not
            # fail the new gang's hello.
            pass
        try:
            import jax.extend.backend as _jeb

            _jeb.clear_backends()
        except Exception:  # pragma: no cover — best effort on older jax
            pass


def _rendezvous_strict() -> bool:
    return os.environ.get("RT_RENDEZVOUS_STRICT", "").lower() in (
        "1", "true", "yes", "on")


def _cpu_only_backend() -> bool:
    """True when this process's jax sees no accelerator platform (the
    CPU-graceful degrade gate). Conservative: unknown -> True only for
    explicit JAX_PLATFORMS=cpu; a probe failure assumes accelerators."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return True
    try:
        import jax

        return jax.default_backend() == "cpu"
    except Exception:  # noqa: BLE001 — can't tell: don't mask a TPU gang
        return False


def clear_rendezvous(group_name: str = "train") -> None:
    from ray_tpu.core.worker import global_worker

    global_worker()._require_backend().kv_del(_kv_key(group_name))


def bootstrap_torch_distributed(world_size: int, rank: int,
                                group_name: str = "train",
                                backend_name: str = "gloo",
                                timeout_s: float = 60.0) -> None:
    """torch.distributed process-group bootstrap through the same GCS-KV
    rendezvous (reference: ``train/torch/config.py:64`` —
    ``_setup_torch_process_group`` with rank-0 TCP store). CPU torch uses
    gloo; the coordinator address rides the KV exactly like the jax path."""
    import ray_tpu  # noqa: F401 — backend access below
    from ray_tpu.core.worker import global_worker

    if world_size <= 1:
        return
    backend = global_worker()._require_backend()
    key = _kv_key(f"torch/{group_name}")
    address = _publish_or_await_coordinator(
        backend, key, rank, None, timeout_s,
        f"torch rendezvous {group_name!r}")
    import datetime

    import torch.distributed as dist

    host, port = address.rsplit(":", 1)
    dist.init_process_group(
        backend_name, init_method=f"tcp://{host}:{port}",
        rank=rank, world_size=world_size,
        timeout=datetime.timedelta(seconds=timeout_s))
    if rank == 0:
        try:
            backend.kv_del(key)
        except Exception:  # noqa: BLE001
            pass
