"""Collective communication.

Reference analog: ``python/ray/util/collective/`` (NCCL/Gloo groups between
named actors, ``collective.py:120-621``). TPU-native redesign in two planes:

1. **Device plane (the fast path)** — collectives are NOT a runtime service:
   they are XLA ops (psum/all_gather/ppermute/reduce_scatter) compiled into
   jitted programs over a ``jax.sharding.Mesh``, riding ICI within a slice
   and DCN across slices. The runtime's job is only bootstrap:
   ``rendezvous.bootstrap_jax_distributed`` wires multi-host processes
   together through the GCS KV (the reference's unique-id rendezvous via a
   named actor, ``nccl_util.py``, same trick).
2. **Host plane (the compatibility path)** — ``allreduce``/``broadcast``/
   ``send``/``recv``/... on host numpy arrays between actors/tasks: ring
   algorithms over direct worker-to-worker RPC links (gloo-equivalent for
   CPU tensors); the rendezvous actor holds membership only.
"""

from ray_tpu.collective.collective import (  # noqa: F401
    allgather,
    allreduce,
    barrier,
    broadcast,
    cancel_shipment,
    create_collective_group,
    destroy_collective_group,
    fetch_params,
    group_stats,
    init_collective_group,
    recv,
    reducescatter,
    send,
    ship_params,
    shipment_receipt,
)
from ray_tpu.collective.rendezvous import bootstrap_jax_distributed  # noqa: F401
