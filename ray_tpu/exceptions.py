"""Public exception types.

Mirrors the capability surface of the reference's ``python/ray/exceptions.py``:
task errors wrap the remote traceback, actor death and object loss are
distinguishable, and ``get`` timeouts are their own type.
"""

from __future__ import annotations

import traceback
from typing import Optional


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at ``get`` with the remote trace.

    Equivalent of the reference's ``RayTaskError``.
    """

    def __init__(self, function_name: str, cause: BaseException, remote_tb: Optional[str] = None):
        self.function_name = function_name
        self.cause = cause
        self.remote_traceback = remote_tb or "".join(
            traceback.format_exception(type(cause), cause, cause.__traceback__)
        )
        super().__init__(
            f"task {function_name} failed: {type(cause).__name__}: {cause}\n"
            f"--- remote traceback ---\n{self.remote_traceback}"
        )

    def __reduce__(self):
        return (
            _rebuild_task_error,
            (self.function_name, type(self.cause).__name__, str(self.cause), self.remote_traceback),
        )


class _RemoteCause(Exception):
    """Stand-in for a remote exception type that may not import locally."""

    def __init__(self, type_name: str, msg: str):
        self.type_name = type_name
        super().__init__(f"{type_name}: {msg}")


def _rebuild_task_error(fn, cause_type, cause_msg, tb):
    return TaskError(fn, _RemoteCause(cause_type, cause_msg), tb)


class ActorError(RayTpuError):
    pass


class ActorDiedError(ActorError):
    """The actor is dead (crashed, killed, or out of restarts)."""

    def __init__(self, actor_id=None, reason: str = ""):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"actor {actor_id} died: {reason}")

    def __reduce__(self):
        # default Exception pickling would reconstruct with the formatted
        # message as actor_id, double-wrapping the text on every serde hop
        return (ActorDiedError, (self.actor_id, self.reason))


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class ActorUnschedulableError(ActorError):
    """The actor stayed PENDING_CREATION/RESTARTING past a caller-supplied
    deadline (e.g. an infeasible resource request on a cluster that will
    never grow). Only raised when a deadline is requested — by default
    callers block like the reference does."""

    def __init__(self, actor_id=None, state: str = "", waited_s: float = 0.0):
        self.actor_id = actor_id
        self.state = state
        self.waited_s = waited_s
        super().__init__(
            f"actor {actor_id} still {state} after {waited_s:.0f}s deadline — "
            f"likely an infeasible resource request (check num_cpus/num_tpus "
            f"against the cluster)")

    def __reduce__(self):
        return (ActorUnschedulableError,
                (self.actor_id, self.state, self.waited_s))


class ObjectLostError(RayTpuError):
    """All copies of an object were lost and it could not be reconstructed."""

    def __init__(self, object_id=None):
        self.object_id = object_id
        super().__init__(f"object {object_id} lost")


class OwnerDiedError(ObjectLostError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get`` exceeded its timeout."""


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"task {task_id} cancelled")


class OutOfMemoryError(RayTpuError):
    """The raylet's memory monitor killed this task's worker to keep the
    node alive (reference: ``worker_killing_policy.cc``; the task is
    retried if it has retries left)."""


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class RuntimeEnvSetupError(RayTpuError):
    pass


class PendingCallsLimitExceeded(RayTpuError):
    pass


class NodeDiedError(RayTpuError):
    pass
