"""Public exception types.

Mirrors the capability surface of the reference's ``python/ray/exceptions.py``:
task errors wrap the remote traceback, actor death and object loss are
distinguishable, and ``get`` timeouts are their own type.
"""

from __future__ import annotations

import traceback
from typing import Optional


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at ``get`` with the remote trace.

    Equivalent of the reference's ``RayTaskError``.
    """

    def __init__(self, function_name: str, cause: BaseException, remote_tb: Optional[str] = None):
        self.function_name = function_name
        self.cause = cause
        self.remote_traceback = remote_tb or "".join(
            traceback.format_exception(type(cause), cause, cause.__traceback__)
        )
        super().__init__(
            f"task {function_name} failed: {type(cause).__name__}: {cause}\n"
            f"--- remote traceback ---\n{self.remote_traceback}"
        )

    def __reduce__(self):
        return (
            _rebuild_task_error,
            (self.function_name, type(self.cause).__name__, str(self.cause), self.remote_traceback),
        )


class _RemoteCause(Exception):
    """Stand-in for a remote exception type that may not import locally."""

    def __init__(self, type_name: str, msg: str):
        self.type_name = type_name
        super().__init__(f"{type_name}: {msg}")


def _rebuild_task_error(fn, cause_type, cause_msg, tb):
    return TaskError(fn, _RemoteCause(cause_type, cause_msg), tb)


class ActorError(RayTpuError):
    pass


class ActorDiedError(ActorError):
    """The actor is dead (crashed, killed, or out of restarts).

    ``cause`` carries the structured death cause (``core/failure.py`` wire
    dict: category, message, restart count, last node) when the GCS knows
    it — so the caller-side error says exactly what ``rt list actors`` and
    ``rt errors`` know, and ``rt trace`` and the exception agree on why.
    """

    def __init__(self, actor_id=None, reason: str = "", cause=None):
        self.actor_id = actor_id
        self.reason = reason
        self.cause_info = dict(cause) if cause else None
        msg = f"actor {actor_id} died: {reason}"
        if self.cause_info:
            extras = [f"category={self.cause_info.get('category')}"]
            if self.cause_info.get("num_restarts") is not None:
                extras.append(
                    f"restarts={self.cause_info['num_restarts']}")
            if self.cause_info.get("node_id"):
                extras.append(
                    f"last_node={str(self.cause_info['node_id'])[:8]}")
            msg += f" ({', '.join(extras)})"
        super().__init__(msg)

    def __reduce__(self):
        # default Exception pickling would reconstruct with the formatted
        # message as actor_id, double-wrapping the text on every serde hop
        return (ActorDiedError, (self.actor_id, self.reason,
                                 self.cause_info))


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class ActorUnschedulableError(ActorError):
    """The actor stayed PENDING_CREATION/RESTARTING past a caller-supplied
    deadline (e.g. an infeasible resource request on a cluster that will
    never grow). Only raised when a deadline is requested — by default
    callers block like the reference does."""

    def __init__(self, actor_id=None, state: str = "", waited_s: float = 0.0):
        self.actor_id = actor_id
        self.state = state
        self.waited_s = waited_s
        super().__init__(
            f"actor {actor_id} still {state} after {waited_s:.0f}s deadline — "
            f"likely an infeasible resource request (check num_cpus/num_tpus "
            f"against the cluster)")

    def __reduce__(self):
        return (ActorUnschedulableError,
                (self.actor_id, self.state, self.waited_s))


class ObjectLostError(RayTpuError):
    """All copies of an object were lost and it could not be reconstructed."""

    def __init__(self, object_id=None, cause=None):
        self.object_id = object_id
        self.cause_info = dict(cause) if cause else None
        msg = f"object {object_id} lost"
        if self.cause_info and self.cause_info.get("message"):
            msg += f": {self.cause_info['message']}"
        super().__init__(msg)

    def __reduce__(self):
        return (type(self), (self.object_id, self.cause_info))


class OwnerDiedError(ObjectLostError):
    """The object's owner process died — its memory-store copy and lineage
    are gone with it (reference: ``OWNER_DIED`` in common.proto)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get`` exceeded its timeout."""


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"task {task_id} cancelled")


class OutOfMemoryError(RayTpuError):
    """The raylet's memory monitor killed this task's worker to keep the
    node alive (reference: ``worker_killing_policy.cc``; the task is
    retried if it has retries left)."""


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class BackpressureError(RayTpuError):
    """The raylet bounced this submission: the task's scheduling class is
    at its admission bound (``RT_MAX_QUEUED_PER_CLASS``) and the task
    opted into fail-fast via ``.options(on_overload="fail")``. Default
    submissions never see this — they block with backoff until the queue
    drains."""

    def __init__(self, message: str = "", queue_depth=None, limit=None):
        self.queue_depth = queue_depth
        self.limit = limit
        super().__init__(message or "task rejected under overload "
                                    "(scheduling-class queue at bound)")

    def __reduce__(self):
        return (BackpressureError,
                (self.args[0] if self.args else "",
                 self.queue_depth, self.limit))


class SchedulingTimeoutError(RayTpuError):
    """The task's ``deadline_s`` budget expired while it was still queued
    in the raylet — the work was shed instead of executed late.
    ``cause_info`` carries the structured ``scheduling_timeout`` cause
    (core/failure.py wire dict) so the raised error and ``rt errors``
    agree on why."""

    def __init__(self, message: str = "", cause=None):
        self.cause_info = dict(cause) if cause else None
        super().__init__(message or "scheduling deadline exceeded in the "
                                    "raylet queue")

    def __reduce__(self):
        return (SchedulingTimeoutError,
                (self.args[0] if self.args else "", self.cause_info))


class RuntimeEnvSetupError(RayTpuError):
    pass


class PendingCallsLimitExceeded(RayTpuError):
    pass


class NodeDiedError(RayTpuError):
    """A node (its raylet) died; tasks/actors/objects there are gone."""

    def __init__(self, node_id=None, cause=None):
        self.node_id = node_id
        self.cause_info = dict(cause) if cause else None
        msg = f"node {node_id} died"
        if self.cause_info and self.cause_info.get("message"):
            msg += f": {self.cause_info['message']}"
        super().__init__(msg)

    def __reduce__(self):
        return (NodeDiedError, (self.node_id, self.cause_info))
