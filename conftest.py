"""Root conftest: path setup only (platform scrubbing is in
``rt_test_platform.py``, loaded as an early ``-p`` plugin via pytest.ini)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
